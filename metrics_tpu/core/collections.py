"""MetricCollection — many metrics, one update call, one fused sync.

Behavioral analogue of the reference's ``torchmetrics/collections.py:26-235``.
TPU upgrade: :meth:`pure_forward` traces *all* member metrics' update + sync +
compute into a single XLA program, so a collection costs one fused reduction
over the mesh instead of one gather per metric (the BASELINE north star).
"""
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Hashable, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

from metrics_tpu.core.metric import _ON_ERROR_MODES, Metric, _copy_state_value
from metrics_tpu.parallel.health import FUSED_KEY_SEP as _FUSED_KEY_SEP
from metrics_tpu.utils.exceptions import MetricsTPUUserError, SyncError


class MetricCollection(dict):
    """An ordered dict of metrics sharing a single ``update``/``forward``
    call — pass the superset of inputs once and each member picks the
    keyword arguments its ``update`` signature accepts.

    Beyond convenience, the collection is the performance seam: its
    ``pure_forward``/``pure_update`` trace every member into ONE XLA
    program, so a whole collection's update costs one fused kernel launch
    and its distributed sync batches into one collective round — the
    design BASELINE's north-star (<1% metric overhead) is built on.
    On the host path, :meth:`sync` combines every member's states into a
    single bucketed plan (``parallel/bucketing.py``): one health header
    plus one collective per dtype/fx class for the WHOLE collection —
    O(#dtypes × #fx-classes) instead of O(#metrics × #leaves) — with
    results bit-identical to the per-member loop and the same
    all-or-nothing / per-member-degradation failure semantics
    (``METRICS_TPU_FUSED_SYNC=0`` restores the per-member loop).
    ``clone(prefix=...)`` gives cheap train/val/test copies.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricCollection, Precision
        >>> mc = MetricCollection({
        ...     "acc": Accuracy(num_classes=3),
        ...     "prec": Precision(num_classes=3, average="macro"),
        ... })
        >>> vals = mc(jnp.asarray([0, 2, 1]), jnp.asarray([0, 1, 1]))
        >>> print({k: round(float(v), 4) for k, v in sorted(vals.items())})
        {'acc': 0.6667, 'prec': 0.6667}

    Args:
        metrics: one Metric, a list/tuple of Metrics, or a dict name->Metric.
        prefix / postfix: added to every key in the output dict.
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def add_metrics(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = type(metric).__name__
                    if name in self:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def items(self, keep_base: bool = True) -> Iterable[Tuple[str, Metric]]:  # type: ignore[override]
        """Default keeps base keys (dict protocol — deepcopy/pickle iterate
        this); pass ``keep_base=False`` for the prefixed/postfixed view."""
        if keep_base:
            return super().items()
        return [(self._set_name(k), v) for k, v in super().items()]

    def keys(self, keep_base: bool = True) -> Iterable[str]:  # type: ignore[override]
        if keep_base:
            return super().keys()
        return [self._set_name(k) for k in super().keys()]

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return {
            self._set_name(k): m(*args, **m._filter_kwargs(**kwargs))
            for k, m in super().items()
        }

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:  # type: ignore[override]
        for m in self.values():
            m.update(*args, **m._filter_kwargs(**kwargs))

    def compute(self) -> Dict[str, Any]:
        return {self._set_name(k): m.compute() for k, m in super().items()}

    def reset(self) -> None:
        for m in self.values():
            m.reset()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self.values():
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, m in super().items():
            out.update(m.state_dict(prefix=f"{k}."))
        return out

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        for k, m in super().items():
            m.load_state_dict(state_dict, prefix=f"{k}.")

    # ---------------- host sync (fault-tolerance aware) ----------------

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        on_error: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> None:
        """Host-sync every member, threading the fault-tolerance knobs.

        Default transport is the **collection-fused** path: all members'
        states combine into one key-prefixed dict and sync through a single
        bucketed plan (``parallel/bucketing.py``) — one health header plus
        one collective per dtype/fx class for the WHOLE collection, instead
        of O(#metrics × #leaves). ``METRICS_TPU_FUSED_SYNC=0`` (or any
        member's ``sync_fused=False``) restores the per-member loop.

        Failure semantics are preserved from the per-member protocol:

        - all-or-nothing under ``on_error="raise"`` — the fused sync raises
          before any member state is touched (no rollback needed); on the
          per-member loop, already-synced members are rolled back before
          the error propagates, so the collection is never left half-synced;
        - under ``"local"``/``"warn"`` a failed fused sync falls back to the
          per-member loop so each member degrades *independently* — healthy
          members still report global values while sick ones keep local
          state (``Metric.sync`` swallows the error per member).
        """
        if on_error is not None and on_error not in _ON_ERROR_MODES:
            raise MetricsTPUUserError(
                f"`on_error` must be one of {_ON_ERROR_MODES}, got {on_error!r}"
            )
        if should_sync and dist_sync_fn is None and self._fused_sync_eligible(distributed_available):
            try:
                self._sync_fused(timeout=timeout)
                return
            except SyncError:
                modes = [
                    on_error if on_error is not None else getattr(m, "sync_on_error", "raise")
                    for m in self.values()
                ]
                if all(mode == "raise" for mode in modes):
                    raise  # nothing was synced: all-or-nothing holds trivially
                # degradation requested somewhere: re-run per member so each
                # applies its own on_error (healthy members still get global
                # values; the verify outcome is identical on every rank, so
                # all ranks fall back together and collectives stay aligned)
        synced: List[Metric] = []
        try:
            for m in self.values():
                m.sync(
                    dist_sync_fn=dist_sync_fn,
                    should_sync=should_sync,
                    distributed_available=distributed_available,
                    on_error=on_error,
                    timeout=timeout,
                )
                if m._is_synced:
                    synced.append(m)
        except Exception:
            for m in synced:
                m.unsync()
            raise

    def _fused_sync_eligible(self, distributed_available: Optional[Callable]) -> bool:
        """Can this collection sync through one combined bucketed plan?

        Requires the built-in transport on every member (no ``dist_sync_fn``,
        no ``process_group``), a distributed world, no member already synced
        (the per-member loop raises the proper "already synced" error), and
        the fused knob on (env default; any member's ``sync_fused=False``
        opts the whole collection out).
        """
        from metrics_tpu.parallel.bucketing import fused_sync_enabled

        members = list(self.values())
        if not members or not fused_sync_enabled():
            return False
        if any(
            m.dist_sync_fn is not None
            or m.process_group is not None
            or m._is_synced
            or getattr(m, "sync_fused", None) is False
            # strict update-count checking is per member: the combined
            # header carries one summed count column, which would escalate
            # strictness onto non-strict members (and opposite-direction
            # skews could cancel in the sum) — strict members keep the
            # per-member loop's exact semantics
            or getattr(m, "sync_strict_update_count", False)
            for m in members
        ):
            return False
        if any(_FUSED_KEY_SEP in key for key in self.keys()):
            return False
        for m in members:
            avail = (
                distributed_available
                if distributed_available is not None
                else m.distributed_available_fn
            )
            if not avail():
                return False
        return True

    def _sync_fused(self, timeout: Optional[float] = None) -> None:
        """One bucketed plan over every member's states.

        The combined header's ``update_count`` column carries the SUM of
        member counts — a best-effort skew indicator only (opposite-
        direction member skews can cancel), which is why strict-mode
        members are excluded from fused eligibility and keep the exact
        per-member check. Raises the typed ``SyncError`` before any member
        state is mutated — all-or-nothing without rollback.
        """
        from metrics_tpu.parallel.sync import host_sync_state

        members = list(super().items())
        combined: Dict[str, Any] = {}
        reductions: Dict[str, Any] = {}
        for key, m in members:
            for name, value in m._state.items():
                combined[f"{key}{_FUSED_KEY_SEP}{name}"] = value
                reductions[f"{key}{_FUSED_KEY_SEP}{name}"] = m._reductions.get(name)
        member_timeouts = [
            t for _, m in members if (t := getattr(m, "sync_timeout", None)) is not None
        ]
        effective_timeout = timeout if timeout is not None else (
            min(member_timeouts) if member_timeouts else None
        )
        synced = host_sync_state(
            combined,
            reductions,
            update_count=sum(getattr(m, "_update_count", 0) for _, m in members),
            timeout=effective_timeout,
            metric_name=f"MetricCollection[{', '.join(k for k, _ in members)}]",
            fused=True,
        )
        # snapshot each member's pre-sync state only now: the sync never
        # mutates its inputs, and a failed attempt (the common case the
        # on_error fallback exists for) must not pay for full state copies
        for key, m in members:
            m._cache = {k: _copy_state_value(v) for k, v in m._state.items()}
            m._sync_degraded = False
            m._restore({name: synced[f"{key}{_FUSED_KEY_SEP}{name}"] for name in m._state})
            m._is_synced = True

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore every synced member's pre-sync local state.

        Members that degraded to local-only state (``on_error="local"``)
        were never marked synced and are skipped rather than raising."""
        if not should_unsync:
            return
        for m in self.values():
            if m._is_synced:
                m.unsync()

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
        on_error: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> Iterator["MetricCollection"]:
        """Collection-wide sync-on-enter / restore-on-exit (the consistent-
        checkpoint pattern), with ``on_error`` graceful degradation."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            should_sync=should_sync,
            distributed_available=distributed_available,
            on_error=on_error,
            timeout=timeout,
        )
        try:
            yield self
        finally:
            self.unsync(should_unsync=should_unsync)

    # ---------------- pure-functional fused path ----------------

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        return {k: m.init_state() for k, m in super().items()}

    def pure_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return {
            k: m.pure_update(state[k], *args, **m._filter_kwargs(**kwargs))
            for k, m in super().items()
        }

    def pure_sync(
        self, state: Dict[str, Any], axis_name: Optional[Any] = None, fused: bool = False
    ) -> Dict[str, Any]:
        """Collective-sync member states over ``axis_name``.

        ``axis_name=None``: each member syncs over its own declared
        ``process_group``; members without one keep their local state (what
        their standalone ``pure_forward`` would do). Raises if no member
        declares a group — there would be nothing to sync. ``fused=True``
        buckets each member's same-dtype/same-fx reduce leaves into one
        collective op (``sync_in_jit`` fused mode)."""
        if axis_name is not None:
            return {k: m.pure_sync(state[k], axis_name, fused=fused) for k, m in super().items()}
        if all(m.process_group is None for m in super().values()):
            raise MetricsTPUUserError(
                "pure_sync needs a mesh axis: pass `axis_name=` or construct "
                "at least one member with `process_group=<axis or tuple>`."
            )
        return {
            k: m.pure_sync(state[k], fused=fused) if m.process_group is not None else state[k]
            for k, m in super().items()
        }

    def pure_compute(self, state: Dict[str, Any]) -> Dict[str, Any]:
        return {self._set_name(k): m.pure_compute(state[k]) for k, m in super().items()}

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        return {k: m.merge_states(a[k], b[k]) for k, m in super().items()}

    def pure_forward(
        self, state: Dict[str, Any], *args: Any, axis_name: Optional[str] = None, **kwargs: Any
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """One fused jittable step for the WHOLE collection: all member
        updates, one round of collectives, all computes — a single XLA graph.

        With ``axis_name=None`` each member syncs over its own declared
        ``process_group`` (members without one stay local) — exactly what the
        member's standalone ``pure_forward`` would do, so mixed-group
        collections neither skip a declared sync nor force one on a
        group-less member."""
        batch = self.pure_update(self.init_state(), *args, **kwargs)
        any_group = any(m.process_group is not None for m in super().values())
        if axis_name is not None or any_group:
            value_state = self.pure_sync(batch, axis_name)
        else:
            value_state = batch
        values = self.pure_compute(value_state)
        new_state = self.merge_states(state, batch)
        return new_state, values

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "(\n"
        for k, v in super().items():
            repr_str += f"  ({k}): {repr(v)}\n"
        return repr_str + ")"
