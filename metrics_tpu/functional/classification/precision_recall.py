"""Precision / Recall — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/precision_recall.py``. Absent-class
exclusion uses ``-1`` denominator flags (static shape) instead of boolean-mask
indexing, so everything jits.
"""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _reduce_stat_scores,
    _stat_scores_update,
)
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod


def _mask_absent_classes(
    numerator: Array, denominator: Array, tp: Array, fp: Array, fn: Array,
    average: Optional[str], mdmc_average: Optional[str],
) -> Tuple[Array, Array]:
    """Flag classes absent from preds AND target with -1 (macro: excluded
    from the mean; none: reported as nan) — jit-safe replacement for the
    reference's dynamic filtering (``precision_recall.py:55-64``)."""
    if mdmc_average != MDMCAverageMethod.SAMPLEWISE and average in (
        AverageMethod.MACRO,
        AverageMethod.NONE,
        None,
    ):
        absent = (tp + fp + fn) == 0
        numerator = jnp.where(absent, -1, numerator)
        denominator = jnp.where(absent, -1, denominator)
    return numerator, denominator


def _precision_compute(
    tp: Array, fp: Array, fn: Array, average: str, mdmc_average: Optional[str]
) -> Array:
    numerator, denominator = _mask_absent_classes(tp, tp + fp, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != "weighted" else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _recall_compute(
    tp: Array, fp: Array, fn: Array, average: str, mdmc_average: Optional[str]
) -> Array:
    numerator, denominator = _mask_absent_classes(tp, tp + fn, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != "weighted" else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _check_prf_args(
    average: Optional[str],
    mdmc_average: Optional[str],
    num_classes: Optional[int],
    ignore_index: Optional[int],
) -> None:
    allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = [None, "samplewise", "global"]
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ["macro", "weighted", "none", None] and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")


def precision(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    r"""Precision :math:`\frac{TP}{TP + FP}` in one stateless call
    (reference ``precision_recall.py:76``) — the functional twin of
    :class:`~metrics_tpu.Precision`.

    Args:
        preds: predictions — labels, probabilities, or logits in any
            supported classification shape (``[N]``, ``[N, C]``,
            ``[N, C, X]``).
        target: ground-truth labels of the matching shape.
        average: ``"micro"`` pools every decision into one tp/fp count;
            ``"macro"`` averages per-class scores equally; ``"weighted"``
            weights them by support; ``"samples"`` scores per sample;
            ``"none"``/``None`` returns the ``[C]`` vector.
        mdmc_average: multidim policy — ``"global"`` flattens the extra
            dimension, ``"samplewise"`` averages per-sample scores,
            ``None`` rejects multidim input.
        ignore_index: class label excluded from every counter.
        num_classes: class count; required for per-class averages.
        threshold: binarization cut for probabilistic input.
        top_k: count top-k multiclass hits instead of argmax only.
        multiclass: force/forbid multiclass interpretation.

    Returns:
        A scalar, or ``[C]`` / ``[N]`` under per-class / samplewise
        reduction.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import precision
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> print(round(float(precision(preds, target, average="macro", num_classes=3)), 4))
        0.2222
    """
    _check_prf_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ["weighted", "none", None] else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return _precision_compute(tp, fp, fn, average, mdmc_average)


def recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    r"""Recall :math:`\frac{TP}{TP + FN}` in one stateless call (reference
    ``precision_recall.py:214``) — the functional twin of
    :class:`~metrics_tpu.Recall`. All arguments behave exactly as
    documented on :func:`precision`; only the compute-time ratio differs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import recall
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> print(round(float(recall(preds, target, average="macro", num_classes=3)), 4))
        0.3333
    """
    _check_prf_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ["weighted", "none", None] else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return _recall_compute(tp, fp, fn, average, mdmc_average)


def precision_recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Both precision and recall from a SINGLE stat-scores pass over the
    inputs (reference ``precision_recall.py:352``) — half the formatting
    and counting work of calling :func:`precision` and :func:`recall`
    separately. Arguments as documented on :func:`precision`.

    Returns:
        ``(precision, recall)`` tuple, each shaped by ``average``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import precision_recall
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> p, r = precision_recall(preds, target, average="macro", num_classes=3)
        >>> print(round(float(p), 4), round(float(r), 4))
        0.2222 0.3333
    """
    _check_prf_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ["weighted", "none", None] else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return (
        _precision_compute(tp, fp, fn, average, mdmc_average),
        _recall_compute(tp, fp, fn, average, mdmc_average),
    )
