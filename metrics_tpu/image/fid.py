"""Fréchet Inception Distance — analogue of reference
``torchmetrics/image/fid.py`` (284 LoC), fully on-device.

Key redesigns vs the reference:

- **Feature extractor is an XLA graph** (`InceptionFeatureExtractor`), not a
  wrapped third-party torch module (reference ``fid.py:26-55``).
- **No host escape:** the Fréchet trace term runs on-device via an eigh-based
  ``trace(sqrtm(S1 S2))`` (see :mod:`metrics_tpu.ops.linalg`) instead of
  shipping a 2048x2048 matrix to CPU scipy (reference ``fid.py:58-93``).
- **Constant-memory option:** ``streaming=True`` accumulates the Gaussian
  sufficient statistics (feature sum, outer-product sum, count) as psum-able
  sum states instead of buffering every feature row (the reference warns
  about its O(samples x 2048) buffer, ``fid.py:224-228``). The default
  mirrors the reference's buffered design, which supports uneven gathers.
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.models.inception import InceptionFeatureExtractor
from metrics_tpu.ops.linalg import kahan_add, kahan_merge, trace_sqrtm_product
from metrics_tpu.utils.data import dim_zero_cat

def _high_dtype():
    """Moment dtype — explicit precision story (reference computes covariance
    in real float64, ``fid.py:269-272``; TPU f64 is software-emulated and
    slow): float64 when the user has enabled jax x64 *at call time*, float32
    otherwise — the float32 path is precision-rescued with Kahan-compensated
    streaming sums, validated at the reference's atol=1e-3 vs scipy
    (tests/image/test_fid_precision.py). canonicalize_dtype never warns."""
    return jax.dtypes.canonicalize_dtype(jnp.float64)


def _compute_fid(
    mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, sqrtm_method: str = "auto"
) -> Array:
    r"""Fréchet distance between N(mu1, sigma1) and N(mu2, sigma2):
    ``||mu1-mu2||^2 + Tr(sigma1 + sigma2 - 2 sqrt(sigma1 sigma2))``
    (reference ``fid.py:96-123``)."""
    diff = mu1 - mu2
    tr_covmean = trace_sqrtm_product(sigma1, sigma2, method=sqrtm_method)
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def _mean_cov(features: Array) -> Tuple[Array, Array]:
    n = features.shape[0]
    mean = features.mean(axis=0)
    diff = features - mean
    cov = diff.T @ diff / (n - 1)
    return mean, cov


def _stats_to_mean_cov(
    s: Array, s_comp: Array, ss: Array, ss_comp: Array, n: Array
) -> Tuple[Array, Array]:
    """Mean/covariance from Kahan-compensated sufficient statistics.

    The compensation terms fold back in here (``sum - comp`` is the corrected
    total: Kahan's comp holds the negated lost low-order bits)."""
    total = s - s_comp
    total_outer = ss - ss_comp
    mean = total / n
    cov = (total_outer - n * jnp.outer(mean, mean)) / (n - 1)
    return mean, cov


class FID(Metric):
    r"""Fréchet Inception Distance between real and generated images.

    Args:
        feature: Inception tap (64 | 192 | 768 | 2048) for the default
            extractor, or any callable ``imgs -> [N, D] features``.
        weights: pretrained inception state dict / checkpoint path for the
            default extractor (random init otherwise).
        variant: backbone forward semantics — 'fidelity' (default) is the
            ``inception-v3-compat`` graph the reference's scores are defined
            on (reference ``fid.py:242``; use a torch-fidelity checkpoint);
            'torchvision' for torchvision ``inception_v3`` checkpoints.
        streaming: accumulate (sum, outer-product sum, count) sufficient
            statistics instead of buffering features — constant memory,
            exactly equivalent mean/cov, recommended on TPU.
        feature_dim: feature dimensionality, required for ``streaming=True``
            with a callable ``feature`` (inferred from integer taps).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from metrics_tpu import FID
        >>> rng = np.random.RandomState(0)
        >>> feats = lambda x: x.reshape(x.shape[0], -1)   # stand-in extractor
        >>> fid = FID(feature=feats, feature_dim=16, streaming=True)
        >>> fid.update(jnp.asarray(rng.rand(32, 4, 2, 2).astype(np.float32)), real=True)
        >>> fid.update(jnp.asarray(rng.rand(32, 4, 2, 2).astype(np.float32) * 0.9 + 0.05), real=False)
        >>> print(round(float(fid.compute()), 4))
        0.3715
    """

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        weights: Optional[Any] = None,
        variant: str = "fidelity",
        streaming: bool = False,
        feature_dim: Optional[int] = None,
        sqrtm_method: str = "auto",
        compute_on_step: bool = False,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        # 'auto' = Newton-Schulz on TPU (matmul-only: seconds of compile vs
        # ~100 s/eigh), eigh elsewhere; see ops/linalg.trace_sqrtm_product.
        # Validate NOW: an epoch of feature extraction must not be wasted on
        # a typo that would only surface at compute()
        if sqrtm_method not in ("auto", "eigh", "ns"):
            raise ValueError(
                f"unknown sqrtm method {sqrtm_method!r}; use 'auto', 'eigh' or 'ns'"
            )
        self.sqrtm_method = sqrtm_method
        if callable(feature):
            self.inception = feature
            feat_dim = feature_dim
        elif isinstance(feature, (int, str)) and str(feature) in ("64", "192", "768", "2048"):
            self.inception = InceptionFeatureExtractor(feature=feature, weights=weights, variant=variant)
            feat_dim = int(feature)
        else:
            raise ValueError(
                f"Integer input to argument `feature` must be one of (64, 192, 768, 2048), got {feature}"
            )
        self.streaming = streaming
        if streaming:
            if feat_dim is None:
                raise ValueError(
                    "`streaming=True` requires a known feature dim: pass an integer"
                    " `feature` tap or `feature_dim=` alongside a callable."
                )
            for side in ("real", "fake"):
                self.add_state(f"{side}_sum", jnp.zeros((feat_dim,), dtype=_high_dtype()), dist_reduce_fx="sum")
                self.add_state(
                    f"{side}_outer", jnp.zeros((feat_dim, feat_dim), dtype=_high_dtype()), dist_reduce_fx="sum"
                )
                # Kahan compensation companions — rescue f32 streaming sums
                # over long eval runs; psum composes (comps add per device)
                self.add_state(f"{side}_sum_comp", jnp.zeros((feat_dim,), dtype=_high_dtype()), dist_reduce_fx="sum")
                self.add_state(
                    f"{side}_outer_comp", jnp.zeros((feat_dim, feat_dim), dtype=_high_dtype()), dist_reduce_fx="sum"
                )
                self.add_state(f"{side}_n", jnp.zeros((), dtype=_high_dtype()), dist_reduce_fx="sum")
        else:
            self.add_state("real_features", [], dist_reduce_fx=None)
            self.add_state("fake_features", [], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:  # type: ignore[override]
        features = self.inception(imgs)
        if self.streaming:
            f = features.astype(_high_dtype())
            side = "real" if real else "fake"
            s, c = kahan_add(
                getattr(self, f"{side}_sum"), getattr(self, f"{side}_sum_comp"), f.sum(axis=0)
            )
            setattr(self, f"{side}_sum", s)
            setattr(self, f"{side}_sum_comp", c)
            ss, cc = kahan_add(
                getattr(self, f"{side}_outer"), getattr(self, f"{side}_outer_comp"), f.T @ f
            )
            setattr(self, f"{side}_outer", ss)
            setattr(self, f"{side}_outer_comp", cc)
            setattr(self, f"{side}_n", getattr(self, f"{side}_n") + f.shape[0])
        elif real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def merge_states(self, state_a, state_b):
        """Kahan-aware merge for the streaming moments: the default plain
        ``a + b`` sum-merge (used by forward accumulation / checkpoint
        resume / map-reduce) would drop the compensation rescue."""
        if not self.streaming:
            return super().merge_states(state_a, state_b)
        out = dict(state_a)
        for side in ("real", "fake"):
            for base in ("sum", "outer"):
                t, c = kahan_merge(
                    state_a[f"{side}_{base}"], state_a[f"{side}_{base}_comp"],
                    state_b[f"{side}_{base}"], state_b[f"{side}_{base}_comp"],
                )
                out[f"{side}_{base}"] = t
                out[f"{side}_{base}_comp"] = c
            out[f"{side}_n"] = state_a[f"{side}_n"] + state_b[f"{side}_n"]
        return out

    def compute(self) -> Array:
        """FID over all accumulated features (reference ``fid.py:265-284``);
        moments in the highest available precision."""
        if self.streaming:
            mean1, cov1 = _stats_to_mean_cov(
                self.real_sum, self.real_sum_comp, self.real_outer, self.real_outer_comp, self.real_n
            )
            mean2, cov2 = _stats_to_mean_cov(
                self.fake_sum, self.fake_sum_comp, self.fake_outer, self.fake_outer_comp, self.fake_n
            )
        else:
            real = dim_zero_cat(self.real_features).astype(_high_dtype())
            fake = dim_zero_cat(self.fake_features).astype(_high_dtype())
            mean1, cov1 = _mean_cov(real)
            mean2, cov2 = _mean_cov(fake)
        return _compute_fid(mean1, cov1, mean2, cov2, self.sqrtm_method).astype(jnp.float32)
