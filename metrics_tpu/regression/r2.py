"""R2Score module — analogue of reference ``torchmetrics/regression/r2.py``
(152 LoC)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.r2 import _r2_score_compute, _r2_score_update


class R2Score(Metric):
    r"""R² (coefficient of determination) — the fraction of target
    variance the predictions explain; 1 perfect, 0 the mean-predictor
    baseline, negative worse than the mean.

    Accumulates four streaming moments per output (Σy, Σy², residual sum,
    count) as "sum" states — O(1) memory in samples, one ``psum`` set
    across the mesh, and exact merges for checkpoint resume.

    Args:
        num_outputs: number of regression outputs ``D`` (default 1).
        adjusted: degrees-of-freedom correction for this many regressors
            (see :func:`~metrics_tpu.functional.r2_score`).
        multioutput: ``"uniform_average"`` / ``"raw_values"`` /
            ``"variance_weighted"`` collapse of the per-output scores.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Raises:
        ValueError: negative ``adjusted`` or unknown ``multioutput``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import R2Score
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> r2 = R2Score()
        >>> print(round(float(r2(preds, target)), 4))
        0.9486
    """

    is_differentiable = True

    def __init__(
        self,
        num_outputs: int = 1,
        adjusted: int = 0,
        multioutput: str = "uniform_average",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput
        shape = () if num_outputs == 1 else (num_outputs,)
        self.add_state("sum_squared_error", jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("sum_error", jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("residual", jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        sum_squared_obs, sum_obs, rss, n_obs = _r2_score_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + rss
        self.total = self.total + n_obs

    def compute(self) -> Array:
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )
