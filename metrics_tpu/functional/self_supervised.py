"""Pairwise embedding similarity — analogue of reference
``torchmetrics/functional/self_supervised.py`` (56 LoC)."""
import jax.numpy as jnp
from jax import Array


def embedding_similarity(
    batch: Array, similarity: str = "cosine", reduction: str = "none", zero_diagonal: bool = True
) -> Array:
    """Pairwise representation similarity matrix.

    Args:
        batch: embeddings ``[batch, dim]``.
        similarity: ``'dot'`` or ``'cosine'``.
        reduction: ``'none'`` | ``'sum'`` | ``'mean'`` along the last dim.
        zero_diagonal: zero self-similarities.

    Example:
        >>> import jax.numpy as jnp
        >>> embeddings = jnp.array([[1., 2., 3., 4.], [1., 2., 3., 4.], [4., 5., 6., 7.]])
        >>> sim = embedding_similarity(embeddings)
        >>> sim.shape
        (3, 3)
    """
    if similarity == "cosine":
        batch = batch / jnp.linalg.norm(batch, axis=1, keepdims=True)
    sqr_mtx = batch @ batch.T
    if zero_diagonal:
        sqr_mtx = sqr_mtx * (1 - jnp.eye(sqr_mtx.shape[0], dtype=sqr_mtx.dtype))
    if reduction == "mean":
        sqr_mtx = jnp.mean(sqr_mtx, axis=-1)
    elif reduction == "sum":
        sqr_mtx = jnp.sum(sqr_mtx, axis=-1)
    return sqr_mtx
