"""Shared random classification fixtures.

Analogue of the reference's `tests/classification/inputs.py`: Input named
tuples shaped [NUM_BATCHES, BATCH_SIZE, ...] per input-type case.
"""
from collections import namedtuple

import numpy as np

from tests.helpers import seed_all
from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES

seed_all(42)

Input = namedtuple("Input", ["preds", "target"])


def _rand(*shape):
    return np.random.rand(*shape).astype(np.float32)


def _randint(hi, *shape):
    return np.random.randint(0, hi, shape)


_input_binary_prob = Input(
    preds=_rand(NUM_BATCHES, BATCH_SIZE), target=_randint(2, NUM_BATCHES, BATCH_SIZE)
)
_input_binary = Input(
    preds=_randint(2, NUM_BATCHES, BATCH_SIZE), target=_randint(2, NUM_BATCHES, BATCH_SIZE)
)
_input_binary_logits = Input(
    preds=(2 * np.random.randn(NUM_BATCHES, BATCH_SIZE)).astype(np.float32),
    target=_randint(2, NUM_BATCHES, BATCH_SIZE),
)
_input_multilabel_prob = Input(
    preds=_rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES),
    target=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES),
)
_input_multilabel = Input(
    preds=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES),
    target=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES),
)
_input_multilabel_multidim_prob = Input(
    preds=_rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM),
    target=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM),
)
_input_multilabel_multidim = Input(
    preds=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM),
    target=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM),
)

__mc_prob_preds = _rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)
__mc_prob_preds = __mc_prob_preds / __mc_prob_preds.sum(axis=2, keepdims=True)
_input_multiclass_prob = Input(
    preds=__mc_prob_preds.astype(np.float32), target=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE)
)
_input_multiclass_logits = Input(
    preds=(3 * np.random.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32),
    target=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE),
)
_input_multiclass = Input(
    preds=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE),
    target=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE),
)

__mdmc_prob_preds = _rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)
__mdmc_prob_preds = __mdmc_prob_preds / __mdmc_prob_preds.sum(axis=2, keepdims=True)
_input_multidim_multiclass_prob = Input(
    preds=__mdmc_prob_preds.astype(np.float32),
    target=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE, EXTRA_DIM),
)
_input_multidim_multiclass = Input(
    preds=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE, EXTRA_DIM),
    target=_randint(NUM_CLASSES, NUM_BATCHES, BATCH_SIZE, EXTRA_DIM),
)

_input_multilabel_logits = Input(
    preds=(2 * np.random.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32),
    target=_randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES),
)

# edge case: every prediction wrong (scores like precision are 0/undefined)
__no_match_preds = _randint(2, NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)
_input_multilabel_no_match = Input(preds=__no_match_preds, target=1 - __no_match_preds)


def generate_plausible_inputs_multilabel(num_classes=NUM_CLASSES, num_batches=NUM_BATCHES, batch_size=BATCH_SIZE):
    """Probs correlated with targets (reference `inputs.py:97-110`) — exercises
    the non-degenerate regime where curve metrics are informative."""
    correct = np.random.randint(0, num_classes, (num_batches, batch_size))
    preds = np.random.rand(num_batches, batch_size, num_classes)
    targets = np.zeros_like(preds, dtype=np.int64)
    np.put_along_axis(targets, correct[..., None], 1, axis=2)
    preds = preds + np.random.rand(num_batches, batch_size, num_classes) * targets / 3
    preds = preds / preds.sum(axis=2, keepdims=True)
    return Input(preds=preds.astype(np.float32), target=targets)


def generate_plausible_inputs_binary(num_batches=NUM_BATCHES, batch_size=BATCH_SIZE):
    targets = np.random.randint(0, 2, (num_batches, batch_size))
    preds = np.random.rand(num_batches, batch_size) + np.random.rand(num_batches, batch_size) * targets / 3
    return Input(preds=(preds / (preds.max() + 0.01)).astype(np.float32), target=targets)


_input_multilabel_prob_plausible = generate_plausible_inputs_multilabel()
_input_binary_prob_plausible = generate_plausible_inputs_binary()

# multiclass probs where one class never appears in the targets (reference's
# "randomly remove one class" case — macro averages must handle 0 support)
__missing_preds = _rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)
__missing_preds = __missing_preds / __missing_preds.sum(axis=2, keepdims=True)
__missing_target = _randint(NUM_CLASSES - 1, NUM_BATCHES, BATCH_SIZE)  # class C-1 absent
_input_multiclass_with_missing_class = Input(
    preds=__missing_preds.astype(np.float32), target=__missing_target
)
