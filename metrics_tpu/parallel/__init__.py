from metrics_tpu.parallel.async_sync import (
    STALENESS_POLICIES,
    AsyncSyncRound,
    drain_round,
    launch_round,
    resolve_round,
    sync_channel,
)
from metrics_tpu.parallel.bucketing import (
    SyncPlan,
    build_sync_plan,
    clear_sync_plan_cache,
    fused_sync_enabled,
    host_sync_state_bucketed,
    sync_plan_cache_info,
)
from metrics_tpu.parallel.health import (
    NONFINITE_STATE,
    build_health_word,
    call_with_sync_watchdog,
    distributed_initialize_with_retry,
    get_sync_timeout,
    verify_health_words,
)
from metrics_tpu.parallel.sync import (
    class_reduce,
    gather_all_arrays,
    host_sync_leaf,
    host_sync_state,
    jit_distributed_available,
    reduce,
    sync_in_jit,
    sync_leaf_in_jit,
)
