"""Property-based fuzzing, part 2: cat-state metrics, retrieval, text.

Targets the runtime's novel paths specifically: batch-split invariance for
CAT-state metrics (CatBuffer/list accumulation + merge is the redesigned
machinery), rank/tie handling vs scipy, segment-op retrieval vs a per-query
numpy loop, and the WER counter vs an independent DP oracle.
"""
import jax.numpy as jnp
import os

import numpy as np
import pytest

# gate, don't crash collection: environments without the fuzzing dep still
# run the rest of the suite (the driver image does not guarantee hypothesis)
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st
from scipy.stats import spearmanr
from sklearn.metrics import average_precision_score

from metrics_tpu import AUROC, RetrievalMAP, SpearmanCorrcoef
from metrics_tpu.functional import retrieval_reciprocal_rank, spearman_corrcoef, wer

N = 24
# CI runs a reduced draw budget to stay inside the 45-min envelope;
# nightly (and any local run without the var) keeps the full budget
_EXAMPLES = int(os.environ.get("METRICS_TPU_FUZZ_EXAMPLES", 30))
COMMON = dict(max_examples=_EXAMPLES, deadline=None)

_scores = st.lists(
    st.floats(0.0, 1.0, allow_nan=False, allow_infinity=False, width=32).filter(
        lambda x: x == 0.0 or x > 1.2e-38  # XLA flushes f32 subnormals (FTZ)
    ),
    min_size=N,
    max_size=N,
)
_bin_target = st.lists(st.integers(0, 1), min_size=N, max_size=N)
# few distinct values -> dense ties, the hard case for rank averaging
_tie_heavy = st.lists(st.sampled_from([0.0, 0.25, 0.5, 0.75, 1.0]), min_size=N, max_size=N)


@settings(**COMMON)
@given(scores=_scores, target=_bin_target, data=st.data())
def test_auroc_cat_state_batch_split_invariance(scores, target, data):
    """AUROC accumulates raw rows in a cat state; its value must not depend
    on how the stream was batched — including through merge_states."""
    t = np.asarray(target)
    if t.min() == t.max():
        return
    s = np.asarray(scores, dtype=np.float32)
    split = data.draw(st.integers(1, N - 1))

    whole = AUROC()
    whole.update(jnp.asarray(s), jnp.asarray(t))

    parts = AUROC()
    parts.update(jnp.asarray(s[:split]), jnp.asarray(t[:split]))
    parts.update(jnp.asarray(s[split:]), jnp.asarray(t[split:]))
    np.testing.assert_allclose(float(whole.compute()), float(parts.compute()), atol=1e-6)

    a, b = AUROC(), AUROC()
    a.update(jnp.asarray(s[:split]), jnp.asarray(t[:split]))
    b.update(jnp.asarray(s[split:]), jnp.asarray(t[split:]))
    a.merge_state(b)  # in-place merge into `a`
    np.testing.assert_allclose(float(a.compute()), float(whole.compute()), atol=1e-6)


@settings(**COMMON)
@given(preds=_tie_heavy, target=_tie_heavy)
def test_spearman_with_dense_ties_matches_scipy(preds, target):
    p = np.asarray(preds, dtype=np.float32)
    t = np.asarray(target, dtype=np.float32)
    if np.std(p) == 0 or np.std(t) == 0:  # correlation undefined
        return
    got = float(spearman_corrcoef(jnp.asarray(p), jnp.asarray(t)))
    want = spearmanr(p, t).statistic
    np.testing.assert_allclose(got, want, atol=1e-5)

    m = SpearmanCorrcoef()
    m.update(jnp.asarray(p[: N // 2]), jnp.asarray(t[: N // 2]))
    m.update(jnp.asarray(p[N // 2 :]), jnp.asarray(t[N // 2 :]))
    np.testing.assert_allclose(float(m.compute()), want, atol=1e-5)


@settings(**COMMON)
@given(
    perm=st.permutations(list(range(N))),
    target=_bin_target,
    qids=st.lists(st.integers(0, 3), min_size=N, max_size=N),
)
def test_retrieval_map_matches_numpy_loop(perm, target, qids):
    """Segment-op MAP vs a per-query numpy loop over arbitrary (possibly
    empty, possibly single-row) query groups, skip policy.

    Scores are a hypothesis-chosen permutation of DISTINCT values: under
    tied scores sklearn's AP is tie-aware (threshold-based) while sort-based
    AP — ours and the reference's `retrieval_average_precision` alike — is
    order-dependent, so ties have no common oracle."""
    s = (np.asarray(perm, dtype=np.float32) + 1.0) / (N + 1)
    t = np.asarray(target)
    q = np.asarray(qids)

    m = RetrievalMAP(empty_target_action="skip")
    m.update(jnp.asarray(s), jnp.asarray(t), indexes=jnp.asarray(q))
    got = float(m.compute())

    scores_per_q = []
    for qid in np.unique(q):
        tq, sq = t[q == qid], s[q == qid]
        if tq.sum() == 0:
            continue
        scores_per_q.append(average_precision_score(tq, sq))
    want = np.mean(scores_per_q) if scores_per_q else 0.0
    np.testing.assert_allclose(got, want, atol=1e-5)


@settings(**COMMON)
@given(scores=_scores, target=_bin_target)
def test_reciprocal_rank_first_hit_property(scores, target):
    """RR == 1/(rank of best-scored positive); brute-forced via argsort."""
    s = np.asarray(scores, dtype=np.float32)
    t = np.asarray(target)
    got = float(retrieval_reciprocal_rank(jnp.asarray(s), jnp.asarray(t)))
    order = np.argsort(-s, kind="stable")
    ranked = t[order]
    hits = np.flatnonzero(ranked)
    want = 0.0 if hits.size == 0 else 1.0 / (hits[0] + 1)
    # ties: our sort may place tied scores in any order; accept any rank
    # within the tie block of the first hit
    if hits.size and np.sum(s == s[order[hits[0]]]) > 1:
        tied_val = s[order[hits[0]]]
        block = np.flatnonzero(s == tied_val)
        lo = np.sum(s > tied_val) + 1
        hi = lo + block.size - 1
        assert any(abs(got - 1.0 / r) < 1e-6 for r in range(lo, hi + 1))
    else:
        np.testing.assert_allclose(got, want, atol=1e-6)


_words = st.lists(st.sampled_from("a b c d aa bb cc".split()), min_size=0, max_size=8)


def _levenshtein(ref, hyp):
    dp = np.arange(len(ref) + 1, dtype=np.int64)
    for j in range(1, len(hyp) + 1):
        prev = dp.copy()
        dp[0] = j
        for i in range(1, len(ref) + 1):
            dp[i] = min(prev[i] + 1, dp[i - 1] + 1, prev[i - 1] + (ref[i - 1] != hyp[j - 1]))
    return dp[-1]


@settings(**COMMON)
@given(refs=st.lists(_words, min_size=1, max_size=4), data=st.data())
def test_wer_matches_dp_oracle(refs, data):
    """WER vs an independent edit-distance DP over random word sequences."""
    refs = [r for r in refs if r]  # empty references are rejected by wer
    if not refs:
        return
    hyps = [data.draw(_words) for _ in refs]
    got = float(wer([" ".join(h) for h in hyps], [" ".join(r) for r in refs]))
    errs = sum(_levenshtein(r, h) for r, h in zip(refs, hyps))
    total = sum(len(r) for r in refs)
    np.testing.assert_allclose(got, errs / total, atol=1e-6)
