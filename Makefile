.PHONY: test test-par test-fast test-ci test-nightly doctest docs bench perf-smoke verify-pretrained lint-metrics clean

# Dev workflow targets (analogue of the reference's Makefile:1-28, minus the
# network-dependent env/pip steps — this image is zero-egress).

clean:
	rm -rf .pytest_cache
	find . -name __pycache__ -type d -prune -exec rm -rf {} +

# full suite on the 8-device virtual CPU mesh (conftest pins the platform)
test:
	python -m pytest tests/ -q -rs

# same suite fanned over 4 xdist workers (each worker gets its own 8-device
# virtual mesh; the persistent compile cache handles concurrent writers).
# NOTE: only worth it on a multi-core host — the current 1-core dev host
# gains nothing from xdist (historical r3 numbers on a since-retired 8-core
# host: 71 min vs 79 min serial; the BERT/model long tail serializes)
test-par:
	python -m pytest tests/ -q -n 4

# skip the slow marks (BERT jit, subprocess DDP, real-weight parity)
test-fast:
	python -m pytest tests/ -q -m "not slow"

# CI suite: representative subset (nightly-marked exhaustive grids excluded)
# under the reference's 45-min envelope + the skip budget, machine-checked
# (scripts/suite_health.py; .github/workflows/ci.yml runs exactly this)
test-ci:
	METRICS_TPU_FUZZ_EXAMPLES=5 python scripts/suite_health.py --max-minutes 45 --max-skips 400 -- \
		python -m pytest tests/ -q -m "not slow and not nightly"

# nightly: the FULL matrix incl. slow marks, same health gate, wider envelope
test-nightly:
	python scripts/suite_health.py --max-minutes 180 --max-skips 400 -- \
		python -m pytest tests/ -q

# docstring examples across the package (also part of `make test` via
# tests/test_doctests.py)
doctest:
	python -m pytest --doctest-modules metrics_tpu -q

# regenerate the per-metric API pages (gated by tests/utils/test_docs_reference.py)
docs:
	python docs/generate_reference.py

# metricslint static contract gate (docs/static_analysis.md): the shipped
# package must lint clean, and every violation fixture must still FAIL —
# a linter that stops finding the planted violations is a broken gate.
# Exit codes are discriminated: only 1 (findings) counts as "fails as
# intended"; 2 (missing path) or an empty glob means the gate itself broke.
lint-metrics:
	python -m metrics_tpu.analysis metrics_tpu/
	@set -e; found=0; for f in tests/analysis/fixtures/violating_*.py; do \
		[ -e "$$f" ] || { echo "lint-metrics: no violation fixtures matched — gate is vacuous"; exit 1; }; \
		found=1; \
		rc=0; python -m metrics_tpu.analysis -q "$$f" >/dev/null 2>&1 || rc=$$?; \
		if [ $$rc -eq 1 ]; then echo "lint-metrics: $$f fails as intended"; \
		elif [ $$rc -eq 0 ]; then echo "lint-metrics: $$f unexpectedly clean — rule regression"; exit 1; \
		else echo "lint-metrics: $$f exited $$rc (expected 1) — gate broken"; exit 1; fi; \
	done; [ $$found -eq 1 ]

# benchmark contract line (TPU when the tunnel is alive, CPU fallback otherwise);
# `--all` additionally runs configs 2-8 (8 = host-sync collective-fusion counts)
bench:
	python bench.py

perf-smoke:
	python -m pytest -m perf -q

# one-command real-weight acceptance (docs/api.md "Pretrained parity checks"):
#   make verify-pretrained FIDELITY_CKPT=... INCEPTION_CKPT=... BERT_DIR=...
# any subset of the three; absent artifacts skip with instructions.
# make vars default from already-exported METRICS_TPU_* env vars so an
# operator's `export METRICS_TPU_FIDELITY_CKPT=...` is honored, not clobbered
FIDELITY_CKPT ?= $(METRICS_TPU_FIDELITY_CKPT)
INCEPTION_CKPT ?= $(METRICS_TPU_INCEPTION_CKPT)
BERT_DIR ?= $(METRICS_TPU_BERT_DIR)
verify-pretrained:
	METRICS_TPU_FIDELITY_CKPT="$(FIDELITY_CKPT)" \
	METRICS_TPU_INCEPTION_CKPT="$(INCEPTION_CKPT)" \
	METRICS_TPU_BERT_DIR="$(BERT_DIR)" \
	python -m pytest tests/models/test_pretrained_parity.py -v -rs
