"""PIT grid vs a scipy linear-sum-assignment reference.

Mirror of the reference's `tests/audio/test_pit.py`: 2- and 3-speaker inputs
× {snr, si_sdr} × eval_func, through class (eager + ddp + per-step sync),
functional, permutate round-trip, and the error contracts. The scipy naive
implementation is the ground truth (`test_pit.py:49-82`).
"""
from collections import namedtuple
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from metrics_tpu import PIT
from metrics_tpu.functional import pit, pit_permutate, si_sdr, snr
from tests.helpers.testers import MetricTester

NUM_BATCHES = 10  # must match tests.helpers.testers.NUM_BATCHES (tester iterates it)
BATCH = 8
TIME = 10
rng = np.random.RandomState(42)

Input = namedtuple("Input", ["preds", "target"])

# 3 speakers exercises the assignment solver; 2 the exhaustive path
inputs3 = Input(
    preds=rng.rand(NUM_BATCHES, BATCH, 3, TIME).astype(np.float32),
    target=rng.rand(NUM_BATCHES, BATCH, 3, TIME).astype(np.float32),
)
inputs2 = Input(
    preds=rng.rand(NUM_BATCHES, BATCH, 2, TIME).astype(np.float32),
    target=rng.rand(NUM_BATCHES, BATCH, 2, TIME).astype(np.float32),
)


def _np_metric(name):
    def _snr(p, t):
        p64, t64 = p.astype(np.float64), t.astype(np.float64)
        return 10 * np.log10(np.sum(t64**2, -1) / np.sum((p64 - t64) ** 2, -1))

    def _si_sdr(p, t):
        p64, t64 = p.astype(np.float64), t.astype(np.float64)
        alpha = np.sum(p64 * t64, -1, keepdims=True) / np.sum(t64**2, -1, keepdims=True)
        s = alpha * t64
        e = p64 - s
        return 10 * np.log10(np.sum(s**2, -1) / np.sum(e**2, -1))

    return _snr if name == "snr" else _si_sdr


def naive_pit_scipy(preds, target, metric_name, eval_func):
    """Reference `test_pit.py:49-82`: full pairwise matrix + scipy assignment."""
    fn = _np_metric(metric_name)
    b, spk = target.shape[0], target.shape[1]
    mtx = np.empty((b, spk, spk))
    for t in range(spk):
        for e in range(spk):
            mtx[:, t, e] = fn(preds[:, e], target[:, t])
    best = []
    for i in range(b):
        row, col = linear_sum_assignment(mtx[i], eval_func == "max")
        best.append(mtx[i, row, col].mean())
    return np.asarray(best)


def _average_pit(preds, target, metric_name, eval_func):
    return naive_pit_scipy(preds, target, metric_name, eval_func).mean()


@pytest.mark.parametrize(
    "preds, target, metric_func, metric_name, eval_func",
    [
        (inputs3.preds, inputs3.target, snr, "snr", "max"),
        (inputs3.preds, inputs3.target, si_sdr, "si_sdr", "max"),
        (inputs2.preds, inputs2.target, snr, "snr", "max"),
        (inputs2.preds, inputs2.target, si_sdr, "si_sdr", "max"),
        (inputs2.preds, inputs2.target, snr, "snr", "min"),
    ],
    ids=["snr3", "si_sdr3", "snr2", "si_sdr2", "snr2_min"],
)
class TestPITMatrix(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_pit_class(self, preds, target, metric_func, metric_name, eval_func, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=PIT,
            sk_metric=partial(_average_pit, metric_name=metric_name, eval_func=eval_func),
            dist_sync_on_step=dist_sync_on_step,
            metric_args=dict(metric_func=metric_func, eval_func=eval_func),
            check_jit=False,  # jit of the exhaustive path is covered below
        )

    def test_pit_functional(self, preds, target, metric_func, metric_name, eval_func):
        for i in range(NUM_BATCHES):
            best, perm = pit(jnp.asarray(preds[i]), jnp.asarray(target[i]), metric_func, eval_func)
            expected = naive_pit_scipy(preds[i], target[i], metric_name, eval_func)
            np.testing.assert_allclose(np.asarray(best), expected, atol=1e-4)

    def test_pit_permutate_roundtrip(self, preds, target, metric_func, metric_name, eval_func):
        """Reordering preds by the returned perm makes the identity
        assignment optimal."""
        p, t = jnp.asarray(preds[0]), jnp.asarray(target[0])
        best, perm = pit(p, t, metric_func, eval_func)
        reordered = pit_permutate(p, perm)
        direct = metric_func(reordered, t)
        np.testing.assert_allclose(np.asarray(direct).mean(), float(np.asarray(best).mean()), atol=1e-4)


def test_error_on_different_shape():
    metric = PIT(snr, "max")
    with pytest.raises(RuntimeError, match="expected to have the same shape"):
        metric(jnp.asarray(rng.rand(3, 3, 10)), jnp.asarray(rng.rand(3, 2, 10)))


def test_error_on_wrong_eval_func():
    metric = PIT(snr, "xxx")
    with pytest.raises(ValueError):
        metric(jnp.asarray(rng.rand(3, 3, 10)), jnp.asarray(rng.rand(3, 3, 10)))


def test_error_on_wrong_shape():
    metric = PIT(snr, "max")
    with pytest.raises(ValueError):
        metric(jnp.asarray(rng.rand(3)), jnp.asarray(rng.rand(3)))


def test_consistency_exhaustive_vs_hungarian():
    """The jitted exhaustive search and the Hungarian host-callback agree
    (reference `test_pit.py:184-196`)."""
    from metrics_tpu.functional.audio.pit import _best_perm_exhaustive, _best_perm_hungarian

    for shp in [(5, 2, 2), (4, 3, 3), (4, 4, 4), (3, 5, 5)]:
        mtx = jnp.asarray(rng.randn(*shp).astype(np.float32))
        bm1, bp1 = _best_perm_exhaustive(mtx, maximize=True)
        bm2, bp2 = _best_perm_hungarian(mtx, maximize=True)
        np.testing.assert_allclose(np.asarray(bm1), np.asarray(bm2), atol=1e-5)
        assert np.array_equal(np.asarray(bp1), np.asarray(bp2))
