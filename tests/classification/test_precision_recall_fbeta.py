"""Precision/Recall/FBeta/F1/Specificity parity vs sklearn."""
import numpy as np
import pytest
from sklearn.metrics import fbeta_score, precision_score, recall_score

from metrics_tpu import F1, FBeta, Precision, Recall, Specificity
from metrics_tpu.functional import f1, fbeta, precision, recall, specificity
from tests.classification.inputs import (
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_prob,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_prec(preds, target, average="micro"):
    return precision_score(
        target, (preds >= THRESHOLD).astype(int) if preds.dtype.kind == "f" and preds.ndim == 1 else preds.argmax(-1) if preds.ndim > 1 else preds,
        average=average, zero_division=0,
    )


def _sk_wrap(sk_fn, preds, target, average, **kw):
    if preds.ndim > target.ndim:  # probs over classes
        y_pred = preds.argmax(-2 if preds.ndim == target.ndim + 2 else -1)
        binary = False
    elif preds.dtype.kind == "f":
        y_pred = (preds >= THRESHOLD).astype(int)
        binary = True
    else:
        y_pred = preds
        binary = False
    # the reference's "micro" on binary inputs scores the positive class
    # only, which is sklearn's average='binary'; macro/weighted over the
    # single class collapse to the same score (r4: converted from skips)
    if binary and average in ("micro", "macro", "weighted"):
        average = "binary"
    return sk_fn(target.ravel(), y_pred.ravel(), average=average, zero_division=0, **kw)


@pytest.mark.parametrize(
    "preds, target",
    [
        (_input_binary_prob.preds, _input_binary_prob.target),
        (_input_multiclass.preds, _input_multiclass.target),
        (_input_multiclass_prob.preds, _input_multiclass_prob.target),
    ],
)
@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
class TestPrecisionRecall(MetricTester):
    atol = 1e-6

    @staticmethod
    def _args(preds, average):
        # fixtures: float [NB, B] = binary probs; int [NB, B] = multiclass
        # labels; [NB, B, C] = multiclass probs. (The old ndim-2 test lumped
        # multiclass LABELS in with binary and skipped their macro/weighted
        # combos entirely — r4 fixed the detection and converted the skips.)
        binary = preds.ndim == 2 and preds.dtype.kind == "f"
        args = {"average": average, "threshold": THRESHOLD}
        if not binary:
            args["num_classes"] = NUM_CLASSES
        elif average != "micro":
            # macro/weighted need an explicit class count; with one class
            # they collapse to the positive-class score (r4: converted from
            # "invalid reference API" skips — valid with num_classes=1)
            args["num_classes"] = 1
        return args

    @pytest.mark.parametrize("ddp", [False, True])
    def test_precision_class(self, ddp, preds, target, average):
        self.run_class_metric_test(
            ddp=ddp, preds=preds, target=target, metric_class=Precision,
            sk_metric=lambda p, t: _sk_wrap(precision_score, p, t, average),
            metric_args=self._args(preds, average),
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_recall_class(self, ddp, preds, target, average):
        self.run_class_metric_test(
            ddp=ddp, preds=preds, target=target, metric_class=Recall,
            sk_metric=lambda p, t: _sk_wrap(recall_score, p, t, average),
            metric_args=self._args(preds, average),
        )

    def test_precision_fn(self, preds, target, average):
        self.run_functional_metric_test(
            preds, target, metric_functional=precision,
            sk_metric=lambda p, t: _sk_wrap(precision_score, p, t, average),
            metric_args=self._args(preds, average),
        )

    def test_recall_fn(self, preds, target, average):
        self.run_functional_metric_test(
            preds, target, metric_functional=recall,
            sk_metric=lambda p, t: _sk_wrap(recall_score, p, t, average),
            metric_args=self._args(preds, average),
        )

    @pytest.mark.parametrize("beta", [0.5, 1.0, 2.0])
    def test_fbeta_class(self, preds, target, average, beta):
        self.run_class_metric_test(
            ddp=False, preds=preds, target=target, metric_class=FBeta,
            sk_metric=lambda p, t: _sk_wrap(fbeta_score, p, t, average, beta=beta),
            metric_args={**self._args(preds, average), "beta": beta},
        )

    @pytest.mark.nightly  # full fixture breadth; CI runs the representative twin below
    def test_f1_sharded(self, preds, target, average):
        self.run_sharded_metric_test(
            preds=preds, target=target, metric_class=F1,
            sk_metric=lambda p, t: _sk_wrap(fbeta_score, p, t, average, beta=1.0),
            metric_args=self._args(preds, average),
        )


def test_specificity_binary():
    """Specificity == recall of the negative class for binary data."""
    import jax.numpy as jnp

    preds = _input_binary_prob.preds[0]
    target = _input_binary_prob.target[0]
    hard = (preds >= THRESHOLD).astype(int)
    tn = int(np.sum((hard == 0) & (target == 0)))
    fp = int(np.sum((hard == 1) & (target == 0)))
    expected = tn / (tn + fp)
    result = specificity(jnp.asarray(preds), jnp.asarray(target), threshold=THRESHOLD)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)


def test_specificity_macro_multiclass():
    import jax.numpy as jnp

    preds = _input_multiclass_prob.preds[0]
    target = _input_multiclass_prob.target[0]
    hard = preds.argmax(-1)
    per_class = []
    for c in range(NUM_CLASSES):
        tn = np.sum((hard != c) & (target != c))
        fp = np.sum((hard == c) & (target != c))
        per_class.append(tn / (tn + fp))
    expected = np.mean(per_class)
    result = specificity(
        jnp.asarray(preds), jnp.asarray(target), average="macro", num_classes=NUM_CLASSES
    )
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)


def test_multilabel_micro_f1():
    import jax.numpy as jnp
    from sklearn.metrics import f1_score

    preds = _input_multilabel_prob.preds[0]
    target = _input_multilabel_prob.target[0]
    expected = f1_score(target.ravel(), (preds >= THRESHOLD).astype(int).ravel(), zero_division=0)
    # multilabel micro in the reference counts each label separately
    result = f1(jnp.asarray(preds), jnp.asarray(target), threshold=THRESHOLD)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)


def test_f1_sharded_ci_representative():
    """CI twin of the nightly full-breadth sharded F1 sweep (macro row)."""
    t = TestPrecisionRecall()
    inp = _input_multiclass_prob
    t.run_sharded_metric_test(
        preds=inp.preds, target=inp.target, metric_class=F1,
        sk_metric=lambda p, tt: _sk_wrap(fbeta_score, p, tt, "macro", beta=1.0),
        metric_args=t._args(inp.preds, "macro"),
    )
