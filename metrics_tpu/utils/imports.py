"""Optional-dependency detection.

TPU-native analogue of the reference's ``torchmetrics/utilities/imports.py:24-84``.
Only packages actually consulted by this framework are probed; everything heavy
(transformers for BERTScore tokenization, nltk for ROUGE stemming) is optional.
"""
import importlib
import operator
from typing import Callable

from packaging.version import Version


def _module_available(module_path: str) -> bool:
    """True if ``module_path`` is importable without importing it eagerly."""
    try:
        return importlib.util.find_spec(module_path) is not None
    except (ModuleNotFoundError, AttributeError, ValueError):
        return False


def _compare_version(package: str, op: Callable, version: str) -> bool:
    try:
        pkg = importlib.import_module(package)
        pkg_version = Version(getattr(pkg, "__version__", "0"))
    except (ImportError, TypeError):
        return False
    return op(pkg_version, Version(version))


_JAX_AVAILABLE = _module_available("jax")
_FLAX_AVAILABLE = _module_available("flax")
_TRANSFORMERS_AVAILABLE = _module_available("transformers")
_NLTK_AVAILABLE = _module_available("nltk")
_ROUGE_SCORE_AVAILABLE = _module_available("rouge_score")
_SCIPY_AVAILABLE = _module_available("scipy")
_TORCH_AVAILABLE = _module_available("torch")
_JAX_GREATER_EQUAL_0_4 = _compare_version("jax", operator.ge, "0.4.0")
