"""Precision / Recall module metrics.

Behavioral analogue of the reference's
``torchmetrics/classification/precision_recall.py`` (326 LoC): both subclass
:class:`StatScores` and reduce at compute time.
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.precision_recall import (
    _precision_compute,
    _recall_compute,
)


class _PrecisionRecallBase(StatScores):
    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        if average in ["macro", "weighted", "none", None] and (not num_classes or num_classes < 1):
            raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average


class Precision(_PrecisionRecallBase):
    r"""Precision :math:`\frac{TP}{TP + FP}` — how much of what the model
    flagged positive actually was positive (reference
    ``precision_recall.py:28``).

    Accumulates the tp/fp/tn/fn counters of :class:`StatScores` across
    batches on-device and reduces them at :meth:`compute`, so the running
    state is four integers per class regardless of dataset size.

    Accepted input forms (auto-detected on the first eager update; the
    detected form is then static for jit):

    - binary labels or probabilities, shape ``[N]``
    - multiclass labels ``[N]`` (int) or per-class scores ``[N, C]``
    - multilabel probabilities ``[N, C]``
    - multidimensional multiclass ``[N, ...]`` / ``[N, C, ...]`` — requires
      ``mdmc_average`` to say how the extra dimension folds in

    Args:
        num_classes: number of classes ``C``. Mandatory whenever the result
            is per-class (``average`` of ``"macro"``/``"weighted"``/
            ``"none"``).
        threshold: probability/logit cut for binarizing probabilistic
            inputs (applied to binary and multilabel scores).
        average: how per-class statistics collapse into the result —
            ``"micro"`` pools all decisions into one tp/fp count before
            dividing; ``"macro"`` averages per-class scores equally;
            ``"weighted"`` weights per-class scores by class support;
            ``"samples"`` scores each sample and averages over samples;
            ``"none"``/``None`` returns the ``[C]`` vector unreduced.
        mdmc_average: policy for inputs with an extra sample dimension:
            ``"global"`` flattens the extra dimension into the batch before
            counting; ``"samplewise"`` computes the metric per sample and
            averages; ``None`` (default) rejects multidim input.
        ignore_index: a class label excluded from every counter (rows whose
            target carries this label contribute nothing).
        top_k: for multiclass score inputs, count a hit if the target is in
            the k highest-scoring classes (default: argmax only).
        multiclass: force (True) or forbid (False) treating ambiguous
            inputs as multiclass, overriding detection.
        compute_on_step: return the batch-local value from ``forward``.
        dist_sync_on_step: all-reduce the counters on every step, not only
            at ``compute`` (useful when logging per-step global values).
        process_group: mesh axis name(s) the sync collectives run over.
        dist_sync_fn: override the gather used by the host-level sync path.

    Raises:
        ValueError: for an unknown ``average``, a per-class ``average``
            without ``num_classes``, multidim input without
            ``mdmc_average``, or inconsistent shapes.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Precision
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> precision = Precision(num_classes=4, average="macro")
        >>> print(round(float(precision(preds, target)), 4))
        0.5
        >>> micro = Precision(average="micro")
        >>> micro.update(jnp.asarray([0.2, 0.8, 0.6]), jnp.asarray([0, 1, 0]))
        >>> print(round(float(micro.compute()), 4))
        0.5
    """

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _precision_compute(tp, fp, fn, self.average, self.mdmc_reduce)


class Recall(_PrecisionRecallBase):
    r"""Recall :math:`\frac{TP}{TP + FN}` — how much of what *is* positive
    the model recovered (reference ``precision_recall.py:180``).

    State, input handling, and every constructor argument behave exactly as
    documented on :class:`Precision`; only the compute-time ratio differs
    (false negatives in the denominator instead of false positives).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Recall
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> recall = Recall(num_classes=4, average="macro")
        >>> print(round(float(recall(preds, target)), 4))
        0.5
        >>> weighted = Recall(num_classes=3, average="weighted")
        >>> weighted.update(jnp.asarray([0, 1, 1, 2]), jnp.asarray([0, 1, 2, 2]))
        >>> print(round(float(weighted.compute()), 4))
        0.75
    """

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _recall_compute(tp, fp, fn, self.average, self.mdmc_reduce)
