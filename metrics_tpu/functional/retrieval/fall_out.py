"""Single-query fall-out@k — analogue of reference
``torchmetrics/functional/retrieval/fall_out.py``."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_retrieval_k, _check_retrieval_functional_inputs


def retrieval_fall_out(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of non-relevant documents among the top ``k`` retrieved."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    _check_retrieval_k(k)
    target = 1 - target
    if not jnp.sum(target):
        return jnp.asarray(0.0)
    nonrelevant = jnp.sum(target[jnp.argsort(-preds)][:k]).astype(jnp.float32)
    return nonrelevant / jnp.sum(target)
