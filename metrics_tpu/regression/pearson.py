"""PearsonCorrcoef module — analogue of reference
``torchmetrics/regression/pearson.py:56-144``.

States are per-device running moments with ``dist_reduce_fx=None`` (gathered,
not summed); the pairwise moment-merge formula (reference ``pearson.py:23-53``)
is exposed both as the cross-device aggregation at compute time AND as this
metric's ``merge_states`` — one algebra for DDP sync, ``forward`` and
checkpoint-resume merging.
"""
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.pearson import (
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)


def _merge_two(
    mx1: Array, my1: Array, vx1: Array, vy1: Array, cxy1: Array, n1: Array,
    mx2: Array, my2: Array, vx2: Array, vy2: Array, cxy2: Array, n2: Array,
) -> Tuple[Array, ...]:
    """Pairwise merge of two running-moment states (reference pearson.py:23-53)."""
    nb = n1 + n2
    mean_x = (n1 * mx1 + n2 * mx2) / nb
    mean_y = (n1 * my1 + n2 * my2) / nb
    var_x = vx1 + vx2 + n1 * (mx1 - mean_x) ** 2 + n2 * (mx2 - mean_x) ** 2
    var_y = vy1 + vy2 + n1 * (my1 - mean_y) ** 2 + n2 * (my2 - mean_y) ** 2
    corr_xy = (
        cxy1 + n1 * (mx1 - mean_x) * (my1 - mean_y)
        + cxy2 + n2 * (mx2 - mean_x) * (my2 - mean_y)
    )
    return mean_x, mean_y, var_x, var_y, corr_xy, nb


def _final_aggregation(
    means_x: Array, means_y: Array, vars_x: Array, vars_y: Array, corrs_xy: Array, nbs: Array
) -> Tuple[Array, Array, Array, Array]:
    """Fold gathered per-device moment vectors into global statistics."""
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx1, my1, vx1, vy1, cxy1, n1 = _merge_two(
            mx1, my1, vx1, vy1, cxy1, n1,
            means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i],
        )
    return vx1, vy1, cxy1, n1


class PearsonCorrcoef(Metric):
    r"""Pearson correlation coefficient between a prediction and target
    stream — linear association in [-1, 1].

    State is five running moments (mean, variance, covariance, count per
    side) with ``dist_reduce_fx=None`` and a pairwise-merge formula
    (Chan et al.-style) supplied via ``merge_state`` — numerically stable
    single-pass accumulation that merges exactly across devices, batches,
    and checkpoint resumes. Expects 1-D inputs; both must be the same
    shape.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrcoef
        >>> preds = jnp.asarray([2.0, 2.0, 2.0, 2.0, 6.0])
        >>> target = jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0])
        >>> pearson = PearsonCorrcoef()
        >>> print(round(float(pearson(preds, target)), 4))
        0.7071
    """

    is_differentiable = True

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.add_state("mean_x", jnp.zeros(()), dist_reduce_fx=None)
        self.add_state("mean_y", jnp.zeros(()), dist_reduce_fx=None)
        self.add_state("var_x", jnp.zeros(()), dist_reduce_fx=None)
        self.add_state("var_y", jnp.zeros(()), dist_reduce_fx=None)
        self.add_state("corr_xy", jnp.zeros(()), dist_reduce_fx=None)
        self.add_state("n_total", jnp.zeros(()), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        """Empty-side-aware pairwise merge, fully traceable.

        Historically this early-returned on ``float(jnp.sum(...)) == 0`` —
        a device→host sync on every ``forward()`` step that also made the
        merge untraceable, so the compiled forward path could never engage
        for Pearson (metricslint: host-sync-in-update). The empty-side
        selection is now a ``jnp.where`` over the merged result: same
        values, no host round-trip, one traceable program.
        """
        n_a = jnp.sum(jnp.atleast_1d(a["n_total"]))
        n_b = jnp.sum(jnp.atleast_1d(b["n_total"]))
        a_empty, b_empty = n_a == 0, n_b == 0
        # a both-empty merge divides 0/0 inside _merge_two; feed it a dummy
        # count so no NaN is ever produced — the result is select()ed away
        n2 = jnp.where(a_empty & b_empty, jnp.ones_like(jnp.asarray(b["n_total"])), b["n_total"])
        mx, my, vx, vy, cxy, n = _merge_two(
            a["mean_x"], a["mean_y"], a["var_x"], a["var_y"], a["corr_xy"], a["n_total"],
            b["mean_x"], b["mean_y"], b["var_x"], b["var_y"], b["corr_xy"], n2,
        )
        merged = {"mean_x": mx, "mean_y": my, "var_x": vx, "var_y": vy, "corr_xy": cxy, "n_total": n}
        return {
            k: jnp.where(b_empty, a[k], jnp.where(a_empty, b[k], merged[k]))
            for k in merged
        }

    def compute(self) -> Array:
        if self.mean_x.ndim > 0 and self.mean_x.shape[0] > 1:
            # gathered multi-device states: fold with the pairwise merge
            var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)
