"""Case-insensitive string enums used across the metric surface.

TPU-native analogue of the reference's ``torchmetrics/utilities/enums.py:18-83``.
"""
from enum import Enum
from typing import Optional, Union


class EnumStr(str, Enum):
    """String-valued enum with case-insensitive ``from_str`` lookup."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other: Union[str, Enum, None]) -> bool:  # type: ignore[override]
        other = other.value if isinstance(other, Enum) else str(other)
        return self.value.lower() == other.lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """The kind of classification input detected by input formatting."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """How per-class statistics are averaged into a final score."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """How the extra sample dimension of multi-dim multi-class inputs is handled."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"
