"""Property-based fuzzing, part 3: mathematical invariants.

Each metric family has a defining identity that must hold for ALL inputs —
scale invariance for SI-SNR, SSIM(x,x)=1, KL >= 0 with equality iff p=q,
compositional arithmetic distributing over compute. Hypothesis searches for
violations; shapes stay fixed so everything jits once.
"""
import jax.numpy as jnp
import os

import numpy as np
import pytest

# gate, don't crash collection: environments without the fuzzing dep still
# run the rest of the suite (the driver image does not guarantee hypothesis)
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from metrics_tpu import Accuracy, BootStrapper, MeanSquaredError
from metrics_tpu.functional import (
    cosine_similarity,
    image_gradients,
    kl_divergence,
    psnr,
    si_snr,
    snr,
    ssim,
)

N = 16
# CI runs a reduced draw budget to stay inside the 45-min envelope;
# nightly (and any local run without the var) keeps the full budget
_EXAMPLES = int(os.environ.get("METRICS_TPU_FUZZ_EXAMPLES", 30))
COMMON = dict(max_examples=_EXAMPLES, deadline=None)

_signal = st.lists(
    st.floats(-100.0, 100.0, allow_nan=False, allow_infinity=False, width=32),
    min_size=N,
    max_size=N,
)
_pos_scale = st.floats(0.0078125, 100.0, allow_nan=False, allow_infinity=False, width=32)


@settings(**COMMON)
@given(target=_signal, noise=_signal, scale=_pos_scale)
def test_si_snr_scale_invariance(target, noise, scale):
    """The SI in SI-SNR: rescaling the estimate must not change the value."""
    t = np.asarray(target, np.float32)
    est = t + 0.1 * np.asarray(noise, np.float32)
    if np.sum(t**2) < 1e-6 or np.sum((est - t) ** 2) < 1e-9:
        return  # silent target / exact-match: value is +/-inf territory
    base = float(si_snr(jnp.asarray(est), jnp.asarray(t)))
    if base > 50.0:
        # above ~50 dB the projection residual sits at f32 cancellation
        # level: the invariant still holds mathematically but the computed
        # value is noise-dominated (hypothesis-found at 70-76 dB)
        return
    scaled = float(si_snr(jnp.asarray(est * scale), jnp.asarray(t)))
    np.testing.assert_allclose(base, scaled, rtol=1e-3, atol=1e-3)


@settings(**COMMON)
@given(target=_signal, scale=_pos_scale)
def test_snr_of_scaled_self_matches_closed_form(target, scale):
    """SNR(a*x, x) has the closed form 10*log10(1/(a-1)^2) for a != 1."""
    t = np.asarray(target, np.float32)
    if np.sum(t**2) < 1e-3 or abs(scale - 1.0) < 1e-3:
        return
    got = float(snr(jnp.asarray(scale * t), jnp.asarray(t)))
    want = 10.0 * np.log10(1.0 / (scale - 1.0) ** 2)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-2)


@settings(**COMMON)
@given(seed=st.integers(0, 2**31 - 1))
def test_ssim_self_is_one_and_symmetric(seed):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.rand(1, 1, 16, 16).astype(np.float32))
    y = jnp.asarray(rng.rand(1, 1, 16, 16).astype(np.float32))
    np.testing.assert_allclose(float(ssim(x, x, data_range=1.0)), 1.0, atol=1e-5)
    np.testing.assert_allclose(
        float(ssim(x, y, data_range=1.0)), float(ssim(y, x, data_range=1.0)), atol=1e-5
    )


@settings(**COMMON)
@given(seed=st.integers(0, 2**31 - 1), noise_scale=st.floats(0.0078125, 0.5, width=32))
def test_psnr_decreases_with_noise(seed, noise_scale):
    """PSNR must be monotone: more noise, lower PSNR; and PSNR(x,x) is huge."""
    rng = np.random.RandomState(seed)
    x = rng.rand(1, 1, 8, 8).astype(np.float32)
    noise = rng.randn(1, 1, 8, 8).astype(np.float32)
    small = float(psnr(jnp.asarray(x + noise_scale * 0.1 * noise), jnp.asarray(x), data_range=1.0))
    large = float(psnr(jnp.asarray(x + noise_scale * noise), jnp.asarray(x), data_range=1.0))
    assert small > large


@settings(**COMMON)
@given(
    p_raw=st.lists(st.floats(0.0078125, 1.0, width=32), min_size=8, max_size=8),
    q_raw=st.lists(st.floats(0.0078125, 1.0, width=32), min_size=8, max_size=8),
)
def test_kl_nonnegative_and_zero_iff_equal(p_raw, q_raw):
    p = np.asarray(p_raw, np.float32)[None, :]
    q = np.asarray(q_raw, np.float32)[None, :]
    p, q = p / p.sum(), q / q.sum()
    kl = float(kl_divergence(jnp.asarray(p), jnp.asarray(q)))
    assert kl >= -1e-6
    self_kl = float(kl_divergence(jnp.asarray(p), jnp.asarray(p)))
    np.testing.assert_allclose(self_kl, 0.0, atol=1e-6)


@settings(**COMMON)
@given(a=_signal, b=_signal)
def test_cosine_similarity_bounds(a, b):
    x = np.asarray(a, np.float32)[None, :]
    y = np.asarray(b, np.float32)[None, :]
    if np.linalg.norm(x) < 1e-3 or np.linalg.norm(y) < 1e-3:
        return
    c = float(cosine_similarity(jnp.asarray(x), jnp.asarray(y)))
    assert -1.0 - 1e-5 <= c <= 1.0 + 1e-5
    np.testing.assert_allclose(
        float(cosine_similarity(jnp.asarray(2.0 * x), jnp.asarray(y))), c, atol=1e-4
    )


@settings(**COMMON)
@given(preds=st.lists(st.integers(0, 4), min_size=N, max_size=N),
       target=st.lists(st.integers(0, 4), min_size=N, max_size=N))
def test_compositional_arithmetic_distributes(preds, target):
    """(m_a + m_b).compute() == m_a.compute() + m_b.compute(); same for *."""
    p = jnp.asarray(np.asarray(preds))
    t = jnp.asarray(np.asarray(target))
    acc_a, acc_b = Accuracy(num_classes=5), Accuracy(num_classes=5)
    plus = acc_a + acc_b
    times = acc_a * acc_b
    acc_a.update(p, t)
    acc_b.update(t, t)  # always 1.0
    va, vb = float(acc_a.compute()), float(acc_b.compute())
    np.testing.assert_allclose(float(plus.compute()), va + vb, atol=1e-6)
    np.testing.assert_allclose(float(times.compute()), va * vb, atol=1e-6)


@settings(**COMMON)
@given(seed=st.integers(0, 2**31 - 1))
def test_bootstrapper_deterministic_under_seed(seed):
    """Same PRNG seed -> identical bootstrap statistics (JAX PRNG contract)."""
    rng = np.random.RandomState(7)
    p = jnp.asarray(rng.rand(N).astype(np.float32))
    t = jnp.asarray(rng.rand(N).astype(np.float32))

    outs = []
    for _ in range(2):
        bs = BootStrapper(MeanSquaredError(), num_bootstraps=4, seed=seed)
        bs.update(p, t)
        outs.append({k: np.asarray(v) for k, v in bs.compute().items()})
    for k in outs[0]:
        np.testing.assert_array_equal(outs[0][k], outs[1][k])


@settings(**COMMON)
@given(seed=st.integers(0, 2**31 - 1))
def test_image_gradients_of_linear_ramp(seed):
    """Gradients of a linear ramp are constant = slope (finite differences
    are exact for degree-1 images)."""
    rng = np.random.RandomState(seed)
    sy, sx = rng.uniform(-2, 2, 2).astype(np.float32)
    yy, xx = np.mgrid[0:8, 0:8].astype(np.float32)
    img = (sy * yy + sx * xx)[None, None]
    dy, dx = image_gradients(jnp.asarray(img))
    np.testing.assert_allclose(np.asarray(dy)[0, 0, :-1, :], sy, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dx)[0, 0, :, :-1], sx, atol=1e-4)
