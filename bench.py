"""Benchmarks on the available accelerator.

Default (driver contract): runs BASELINE config 1 and prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}``.

``python bench.py --all`` additionally runs configs 2-15 (one JSON line
each; ``--config N`` runs selected ones — a comma-separated list like
``--config 9,11`` runs several in one process sharing compile-cache warmth;
see BASELINE.md for the config table and BENCH.md for recorded numbers;
config 8 is the host-sync collective-fusion accounting added with the
bucketed planner, config 9 the compute-group update/state dedup accounting,
config 10 the preemption-safe checkpoint snapshot/restore latency +
restore-after-kill equivalence, config 11 the compiled eager hot path —
compiled vs eager step time, dispatch counts and bit-equality, config 12
the async overlapped sync, config 13 the telemetry recorder's hot-path
overhead + trace-export smoke, config 14 the fleet-resilience simulation —
quorum readmission latency after a transient partition plus the
dead-rank degradation curve, config 15 the whole-step fused program —
update + in-jit fused sync + compute as ONE cached XLA dispatch vs the
compiled-update + separate-host-sync composition at simulated W=8).

Timing methodology (see BENCH.md): hot paths are timed **on-chip** by
scanning K steps inside ONE jitted program (``lax.scan``) and dividing — a
per-call python loop measures the host→device dispatch path instead (2.2 ms
per call over this environment's remote-TPU tunnel, which would swamp every
sub-millisecond kernel). Compute paths are warmed once so XLA compile time
(reported separately as a diagnostic) never pollutes a steady-state number.

The baseline proxy for config 1 is a faithful torch-CPU implementation of the
same accumulation (the reference publishes no performance numbers —
BASELINE.md), timed in-process.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

BATCH = 2048
NUM_CLASSES = 10
SCAN_STEPS = 200


def _ensure_backend(probe_timeouts=(240, 60)) -> str:
    """Make sure jax can actually initialize a backend before benching.

    The ambient accelerator plugin (JAX_PLATFORMS=axon tunnel) can fail or
    hang at first contact (round-1 failure: BENCH_r01 rc=1, 'Unable to
    initialize backend'). Probe it in a subprocess with a timeout; on
    persistent failure fall back to cpu so the contract JSON line is still
    emitted with a real (cpu) measurement plus a diagnostic.

    Must run before jax creates a backend in THIS process. Returns the
    platform name actually in use.
    """
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats == "cpu":
        import jax

        # the env var alone is ineffective when jax was PRELOADED before this
        # process's env took effect (site preload) — pin via config too, or
        # jax.devices() would still initialize the ambient accelerator plugin
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
    # empty JAX_PLATFORMS still auto-detects accelerator plugins, so it gets
    # the same timeout-guarded probe as an explicit accelerator setting

    code = "import jax; d = jax.devices(); print('PROBE_OK', d[0].platform)"
    last_err = None
    for probe_timeout in probe_timeouts:
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=probe_timeout,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                import jax

                return jax.devices()[0].platform
            last_err = (r.stdout + r.stderr).strip()[-500:]
        except subprocess.TimeoutExpired:
            last_err = f"backend probe timed out after {probe_timeout}s"
        time.sleep(5)

    print(
        json.dumps(
            {
                "diagnostic": "accelerator backend unavailable, falling back to cpu",
                "error": last_err,
                "tpu_evidence": "BENCH_TPU_r03_raw.jsonl records driver-path TPU runs "
                "from reachable windows; probe_log.txt records the outage",
            }
        ),
        file=sys.stderr,
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def _enable_persistent_compile_cache() -> None:
    """Cache XLA compiles on disk (repo-local ``.jax_cache/``).

    Compiles through the remote-TPU tunnel are the dominant bench cost (e.g.
    287 s for the Inception update program, 35 s cold for a trivial step —
    BENCH_TPU_r03_raw.jsonl); the persistent cache makes every rerun across
    tunnel windows pay steady-state only. Uses the packaged helper
    (`metrics_tpu/utils/compile_cache.py`) pointed at a repo-local dir so
    bench runs are hermetic. NOTE: once the cache is warm, `compile_s`
    diagnostics measure cache-hit deserialization, not cold XLA compile —
    the emitted `compile_cache` diagnostic marks which regime a run was in.
    """
    try:
        from metrics_tpu.utils import compile_cache

        # METRICS_TPU_COMPILE_CACHE overrides the repo-local default (an
        # operator pointing several bench runs at one shared cache dir)
        path = compile_cache.enable_from_env(min_compile_seconds=2)
        if path is None:
            cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
            path = compile_cache.enable(cache_dir, min_compile_seconds=2)
        pre_warmed = bool(os.listdir(path))
        _diag(compile_cache=("warm" if pre_warmed else "cold"), dir=path)
    except Exception as e:  # noqa: BLE001 — cache is an optimization, never fatal
        _diag(compile_cache=f"disabled: {type(e).__name__}: {e}"[:200])


def _diag(**kv) -> None:
    # delegates to the shared helper so the bench diagnostic-line convention
    # has ONE definition (observability.diagnostics.diag) — scripts and
    # bench paths stop re-defining it
    from metrics_tpu.observability.diagnostics import diag

    diag(**kv)


def _emit(metric, value, unit, vs=None):
    print(json.dumps({"metric": metric, "value": value, "unit": unit, "vs_baseline": vs}))


REPS = 3


def _fetch_scalar(tree) -> float:
    """Force completion: reduce every leaf to one scalar and PULL it to host.

    Over the remote-TPU tunnel `block_until_ready` returns before execution
    finishes, so wall-clock timing is only honest if the measurement ends
    with a data-dependent device->host read.
    """
    import jax
    import jax.numpy as jnp

    leaves = [x for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "dtype")]
    total = sum(jnp.sum(jnp.asarray(leaf, jnp.float32)) for leaf in leaves)
    return float(total)


def _time_scan_step(pure_step, state0, k1: int, k2: int):
    """On-chip per-step seconds by SLOPE: (t(k2) - t(k1)) / (k2 - k1).

    Each measurement scans K steps in ONE jitted program and ends with a
    scalar readback; medians over REPS runs cancel the tunnel's 60-150 ms
    per-call jitter, and the slope cancels its mean (BENCH.md).
    Returns (per_step_seconds, compile_seconds, final_state_of_k2).
    """
    import jax
    from jax import lax

    compile_s = 0.0
    medians = {}
    spreads = {}
    final = None
    for k in (k1, k2):

        @jax.jit
        def run(s0, k=k):
            return lax.scan(lambda s, _: (pure_step(s), None), s0, None, length=k)[0]

        t0 = time.perf_counter()
        out = run(state0)
        _fetch_scalar(out)
        compile_s += time.perf_counter() - t0
        ts = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            out = run(state0)
            _fetch_scalar(out)
            ts.append(time.perf_counter() - t0)
        medians[k] = sorted(ts)[len(ts) // 2]
        spreads[k] = max(ts) - min(ts)
        if k == k2:
            final = out
    per_step = max(medians[k2] - medians[k1], 0.0) / (k2 - k1)
    # measurement resolution: tunnel jitter over the step-count difference.
    # a slope below it only bounds the per-step cost from above.
    resolution = max(spreads.values()) / (k2 - k1)
    return per_step, compile_s, resolution, final


def _paired_slope_pair(step_a, step_b, state0, k1: int, k2: int, reps: int = 20):
    """Per-step seconds + per-rep overheads for TWO step functions, with both
    classes of timing error cancelled (the r4 methodology of record):

    - the constant per-call tunnel/dispatch cost (60-150 ms here) cancels by
      SLOPE — each program runs at two scan lengths and the per-step time is
      (t(k2) - t(k1)) / (k2 - k1), so any +c per call drops out (whole-call
      / K timing leaves c/K in the denominator and biases ratios toward 1;
      that bias was caught masquerading as a 4.0->6.8 ms/step "slow window");
    - chip drift between measurements cancels by PAIRING — all four programs
      are compiled up front and every rep runs the full a@k1, b@k1, a@k2,
      b@k2 rotation back-to-back, with the slope and the a-vs-b overhead
      computed WITHIN each rep; the medians over reps (plus the per-rep
      overhead distribution for IQR) are the estimators.

    Returns ((per_step_a_med, per_step_b_med), compile_s, per_rep_overheads)
    where per_rep_overheads lists (b-a)/a per rep, degenerate reps
    (non-positive a-slope under noise) excluded.
    """
    import jax
    from jax import lax

    def make(run_step, k):
        @jax.jit
        def run(s0):
            return lax.scan(lambda s, _: (run_step(s), None), s0, None, length=k)[0]

        return run

    compile_s = 0.0
    runs = {}
    for name, step in (("a", step_a), ("b", step_b)):
        for k in (k1, k2):
            fn = make(step, k)
            t0 = time.perf_counter()
            _fetch_scalar(fn(state0))
            compile_s += time.perf_counter() - t0
            runs[name, k] = fn

    a_steps, b_steps, overheads = [], [], []
    for _ in range(reps):
        t = {}
        for key in (("a", k1), ("b", k1), ("a", k2), ("b", k2)):
            t0 = time.perf_counter()
            _fetch_scalar(runs[key](state0))
            t[key] = time.perf_counter() - t0
        a_s = (t["a", k2] - t["a", k1]) / (k2 - k1)
        b_s = (t["b", k2] - t["b", k1]) / (k2 - k1)
        a_steps.append(a_s)
        b_steps.append(b_s)
        if a_s > 0:
            overheads.append((b_s - a_s) / a_s)
    per_a = max(float(np.median(a_steps)), 0.0)
    per_b = max(float(np.median(b_steps)), 0.0)
    return (per_a, per_b), compile_s, overheads


def _device_step_us(steps, state0, k: int, execs: int = 8):
    """Per-step DEVICE-TIMELINE microseconds for each named step fn — the r5
    method of record for sub-ms programs (VERDICT r4 tasks 1+3).

    Builds a K-step ``lax.scan`` per step fn, warms/compiles OUTSIDE the
    trace, then executes all programs round-robin under ONE
    ``jax.profiler`` trace and reads each execution's duration from the
    *device* timeline (`metrics_tpu/utils/device_trace.py`). Wall clocks
    never enter the number, so host dispatch and tunnel drift cannot bias
    it (the r4 retraction class), and the trace's sub-µs event resolution
    over K steps resolves signals the wall-clock spread could only bound.

    Step names must be unique — device events are matched by the jitted
    function's name. Returns (median_us_per_step, all_us_per_step,
    jitted_programs, compile_seconds). Raises if the backend records no
    device timeline; callers fall back to wall-clock slope.
    """
    import jax
    from jax import lax

    from metrics_tpu.utils.device_trace import measure_device_time_us

    progs = {}
    compile_s = 0.0
    for name, step in steps.items():

        def run(s0, _step=step):
            return lax.scan(lambda s, _: (_step(s), None), s0, None, length=k)[0]

        run.__name__ = name
        fn = jax.jit(run)
        t0 = time.perf_counter()
        _fetch_scalar(fn(state0))
        compile_s += time.perf_counter() - t0
        progs[name] = fn

    res = measure_device_time_us(
        {n: (lambda _fn=fn: _fn(state0)) for n, fn in progs.items()}, execs=execs
    )
    med = {n: m / k for n, (m, _) in res.items()}
    alls = {n: [d / k for d in durs] for n, (_, durs) in res.items()}
    return med, alls, progs, compile_s


def _program_flops(jitted, *args):
    """FLOPs of one execution of a jitted program via XLA cost analysis."""
    ca = jitted.lower(*args).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    f = ca.get("flops")
    return float(f) if f else None


def _peak_flops_bf16(device_kind: str, config=None):
    """Per-chip bf16 peak FLOP/s for MFU denominators (public specs).

    A miss on a real accelerator is reported via ``_diag`` (pass the calling
    config number) so an absent MFU row is attributable to "unknown chip in
    the spec table" rather than "no FLOPs measured" (ADVICE round 5). CPU
    misses are expected (no MFU story) and stay silent.
    """
    table = {
        "TPU v5 lite": 197e12,  # v5e
        "TPU v5e": 197e12,
        "TPU v4": 275e12,
        "TPU v5p": 459e12,
        "TPU v6 lite": 918e12,  # v6e/Trillium
    }
    for k, v in table.items():
        if device_kind.startswith(k):
            return v
    if config is not None and "cpu" not in device_kind.lower():
        _diag(config=config, mfu_peak_unknown_chip=device_kind)
    return None


def _time_repeat_compute(compute_fn, state, perturb, k1: int = 2, k2: int = 10):
    """Per-call seconds of a jittable compute by slope, defeating CSE.

    Runs compute K times inside one scan; `perturb(state, i)` must make each
    iteration's input unique (tiny additive noise) so XLA cannot hoist the
    loop-invariant body. Returns (per_call_s, compile_s, value).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    compile_s = 0.0
    medians = {}
    spreads = {}
    for k in (k1, k2):

        @jax.jit
        def run(s, k=k):
            def body(acc, i):
                out = compute_fn(perturb(s, i))
                leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "dtype")]
                return acc + sum(jnp.sum(jnp.asarray(x, jnp.float32)) for x in leaves), None

            return lax.scan(body, jnp.asarray(0.0), jnp.arange(k))[0]

        t0 = time.perf_counter()
        _ = float(run(state))
        compile_s += time.perf_counter() - t0
        ts = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            _ = float(run(state))
            ts.append(time.perf_counter() - t0)
        medians[k] = sorted(ts)[len(ts) // 2]
        spreads[k] = max(ts) - min(ts)
    per_call = max(medians[k2] - medians[k1], 0.0) / (k2 - k1)
    resolution = max(spreads.values()) / (k2 - k1)
    return max(per_call, resolution), compile_s, compute_fn(state)


def bench_ours() -> float:
    """Config 1: Accuracy + StatScores fused update step (on-chip).

    Primary: device-timeline per-step time (no dispatch, no tunnel, sub-µs
    resolution — resolves the r4 "value == resolution" upper bound into a
    measurement). Wall-clock slope is kept as the cross-check diagnostic.
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricCollection, StatScores

    mc = MetricCollection(
        {"acc": Accuracy(num_classes=NUM_CLASSES), "stats": StatScores(reduce="macro", num_classes=NUM_CLASSES)}
    )
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (BATCH,)))

    # the carry-dependent epsilon (numerically nil at 1e-24) keeps the stat
    # computation INSIDE the loop: with loop-invariant preds XLA's while-loop
    # LICM may hoist the per-step one-hot/compare work and leave only the
    # accumulator adds, which would undercount a real eval loop where every
    # step sees fresh data (same guard as config 7's _step_inputs)
    def step(state):
        chk, s = state
        new = mc.pure_update(s, preds + chk * 1e-24, target)
        bump = sum(
            jnp.sum(leaf.astype(jnp.float32)) for leaf in jax.tree_util.tree_leaves(new)
        )
        return (chk + bump * 1e-24, new)

    import jax

    state0 = (jnp.zeros(()), mc.init_state())
    try:
        med, alls, progs, compile_s = _device_step_us(
            {"cfg1_fused_step": step}, state0, k=2048, execs=8
        )
        per = np.array(alls["cfg1_fused_step"])
        vals = mc.pure_compute(progs["cfg1_fused_step"](state0)[1])
        assert np.isfinite(float(np.asarray(vals["acc"]))), "bench produced non-finite metric"
        # wall-clock slope cross-check (the r2-r4 method)
        wall_us = None
        try:
            wall, _, wall_res, _ = _time_scan_step(step, state0, k1=500, k2=4000)
            wall_us = {"slope_us": round(wall * 1e6, 2), "resolution_us": round(wall_res * 1e6, 2)}
        except Exception as e:  # noqa: BLE001
            wall_us = {"error": str(e)[:120]}
        _diag(
            config=1,
            method="device-trace,k=2048,execs=8",
            compile_s=round(compile_s, 1),
            device_us_per_step=round(float(med["cfg1_fused_step"]), 4),
            device_iqr_us=[
                round(float(np.percentile(per, 25)), 4),
                round(float(np.percentile(per, 75)), 4),
            ],
            resolution_us=round(float(np.percentile(per, 75) - np.percentile(per, 25)), 4),
            wall_cross_check=wall_us,
        )
        return float(med["cfg1_fused_step"]) * 1e-6
    except Exception as e:  # noqa: BLE001 — no device timeline: wall-clock fallback
        _diag(config=1, device_trace_fallback=str(e)[:200])

    per_step, compile_s, resolution, final = _time_scan_step(
        step, state0, k1=500, k2=4000
    )
    vals = mc.pure_compute(final[1])
    assert np.isfinite(float(np.asarray(vals["acc"]))), "bench produced non-finite metric"
    _diag(config=1, compile_s=round(compile_s, 1), resolution_us=round(resolution * 1e6, 2))
    return max(per_step, resolution)


def bench_torch_baseline() -> float:
    """Reference-style accumulation in torch (CPU), same math, same shapes."""
    import torch

    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, (BATCH,)))

    def step(tp, fp, tn, fn, correct, total):
        p1 = preds.argmax(1)
        oh_p = torch.nn.functional.one_hot(p1, NUM_CLASSES)
        oh_t = torch.nn.functional.one_hot(target, NUM_CLASSES)
        true_pred = oh_t == oh_p
        pos_pred = oh_p == 1
        tp = tp + (true_pred & pos_pred).sum(0)
        fp = fp + (~true_pred & pos_pred).sum(0)
        tn = tn + (true_pred & ~pos_pred).sum(0)
        fn = fn + (~true_pred & ~pos_pred).sum(0)
        correct = correct + (p1 == target).sum()
        total = total + target.numel()
        return tp, fp, tn, fn, correct, total

    z = torch.zeros(NUM_CLASSES, dtype=torch.long)
    st = (z, z.clone(), z.clone(), z.clone(), torch.zeros((), dtype=torch.long), 0)
    st = step(*st)  # warm
    t0 = time.perf_counter()
    for _ in range(SCAN_STEPS):
        st = step(*st)
    return (time.perf_counter() - t0) / SCAN_STEPS


def bench_config2() -> None:
    """Config 2: AUROC (CatBuffer cat-state) + ConfusionMatrix collection."""
    import jax.numpy as jnp

    from metrics_tpu import AUROC, ConfusionMatrix, MetricCollection

    batch, steps_cap = 1024, 2048  # 2k steps of 1k rows: 8 MB buffer
    mc = MetricCollection(
        {
            "auroc": AUROC().with_capacity(batch * steps_cap),
            "confmat": ConfusionMatrix(num_classes=2),
        }
    )
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(batch).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, (batch,)))
    mc.update(preds, target)  # warm eager mode detection

    import jax

    # 1 row block in; chk-carry keeps the confmat bincount inside the loop
    # (same LICM guard as configs 1/7 — the CatBuffer append is offset-
    # dependent and safe, but invariant preds would let XLA hoist the rest)
    state0 = (jnp.zeros(()), mc.pure_update(mc.init_state(), preds, target))

    def step(state):
        chk, s = state
        new = mc.pure_update(s, preds + chk * 1e-24, target)
        return (chk + jnp.sum(new["confmat"]["confmat"].astype(jnp.float32)) * 1e-24, new)

    per_step = resolution = None
    try:
        # device-timeline measurement: the K-step scan's device duration has
        # sub-µs resolution, so the CatBuffer append step gets a NUMBER where
        # the r4 wall-clock spread could only give a 6x-disagreeing bound
        med, alls, progs, compile_s = _device_step_us(
            {"cfg2_append_step": step}, state0, k=steps_cap - 1, execs=8
        )
        per = np.array(alls["cfg2_append_step"])
        per_step = float(med["cfg2_append_step"]) * 1e-6
        final = progs["cfg2_append_step"](state0)
        # the device timeline measures each execution directly, so the median
        # IS the number — the IQR is a spread diagnostic, not a resolution
        # floor to clamp against (ADVICE round 5)
        emit_step = per_step
        _diag(config=2, method="device-trace,k=2047,execs=8",
              compile_s=round(compile_s, 1),
              device_us_per_step=round(float(med["cfg2_append_step"]), 4),
              device_iqr_us=[round(float(np.percentile(per, 25)), 4),
                             round(float(np.percentile(per, 75)), 4)])
    except Exception as e:  # noqa: BLE001
        _diag(config=2, device_trace_fallback=str(e)[:200])
        k1, k2 = 255, steps_cap - 1
        per_step, compile_s, resolution, final = _time_scan_step(step, state0, k1=k1, k2=k2)
        upper_bound = per_step < resolution
        # wall-clock slope timing cannot resolve below its measurement
        # resolution, so the clamp stays meaningful here (and only here)
        emit_step = max(per_step, resolution)
        _diag(config=2, compile_s=round(compile_s, 1), upper_bound=upper_bound,
              resolution_us=round(resolution * 1e6, 2))
    final = final[1]  # drop the chk carry
    n_rows = int(np.asarray(final["auroc"]["preds"].count))
    assert n_rows == batch * steps_cap, f"CatBuffer row count {n_rows} != capacity {batch * steps_cap}"
    val = mc.pure_compute(final)
    assert np.isfinite(float(np.asarray(val["auroc"])))

    # reference mechanism, torch-CPU: AUROC keeps growing python-list cat
    # states (classification/auroc.py cat states) and ConfusionMatrix does a
    # bincount scatter-add per step (functional/.../confusion_matrix.py) —
    # timed over the same batch stream (fewer steps, averaged)
    vs = None
    try:
        import torch

        tp = torch.from_numpy(np.asarray(preds))
        tt = torch.from_numpy(np.asarray(target))
        preds_list, target_list = [], []
        confmat = torch.zeros(2, 2)
        base_steps = 512
        t0 = time.perf_counter()
        for _ in range(base_steps):
            preds_list.append(tp)
            target_list.append(tt)
            binary = (tp >= 0.5).long()
            unique = binary * 2 + tt
            confmat += torch.bincount(unique, minlength=4).reshape(2, 2).float()
        base_per_step = (time.perf_counter() - t0) / base_steps
        vs = round(base_per_step / emit_step, 3)
    except Exception:  # noqa: BLE001 — baseline is comparative garnish
        pass
    _emit("auroc_confmat_fused_step", round(emit_step * 1e6, 2), "us/step", vs)

    # Sync-term bound at W=8 (VERDICT r3 weak #6: config 2's multi-host
    # all_gather was extrapolated, never numbered). Multi-chip hardware is
    # unavailable, so split the term into its two parts: (a) the post-gather
    # compaction, MEASURED on this chip over the real [W, cap] gathered
    # shape with the shipped mechanism (ascending contiguous
    # dynamic_update_slice copies, cat_buffer.py — 0.445 ms vs the earlier
    # row-scatter's 113.8 ms, 256x); (b) the ICI transfer, bounded
    # analytically — a ring all_gather of B bytes/device over W devices
    # moves (W-1)/W * B per link, v5e ICI ~45 GB/s/link/direction.
    try:
        from jax import lax

        W = 8
        cap = batch * steps_cap
        bufs = jnp.asarray(rng.rand(W, cap).astype(np.float32))
        counts = jnp.asarray(rng.randint(cap // 2, cap, (W,)), jnp.int32)

        # counts is a jitted ARGUMENT (not a closed-over constant), so the
        # cumsum offsets stay runtime values and the measured compaction
        # matches the shipped sync_cat_buffer_in_jit program, where offsets
        # are data-dependent (ADVICE r4)
        def cfg2_compaction(bufs, counts):
            new_cap = W * cap
            offsets = jnp.cumsum(counts) - counts
            out = jnp.zeros((new_cap,), jnp.float32)
            for r in range(W):
                out = lax.dynamic_update_slice(out, bufs[r], (offsets[r],))
            valid = jnp.arange(new_cap) < jnp.sum(counts)
            return jnp.where(valid, out, 0.0)

        import jax

        jitted = jax.jit(cfg2_compaction)
        t0 = time.perf_counter()
        jax.block_until_ready(jitted(bufs, counts))
        c_s = time.perf_counter() - t0
        try:
            from metrics_tpu.utils.device_trace import measure_device_time_us

            res = measure_device_time_us(
                {"cfg2_compaction": lambda: jitted(bufs, counts)}, execs=10
            )
            per_call = res["cfg2_compaction"][0] * 1e-6
            _diag(config=2, compaction_method="device-trace,execs=10")
        except Exception:  # noqa: BLE001 — wall-clock fallback
            per_call, extra_s, _ = _time_repeat_compute(
                lambda s: cfg2_compaction(*s), (bufs, counts),
                lambda s, i: (s[0] + i * 1e-9, s[1]), k1=1, k2=4,
            )
            c_s += extra_s
        bytes_per_dev = cap * 4 * 2  # preds f32 + target (i32) cat states
        ici_s = (W - 1) / W * bytes_per_dev / 45e9
        _diag(
            config=2,
            sync_term_w8={
                "compaction_ms_measured": round(per_call * 1e3, 3),
                "ici_transfer_ms_bound": round(ici_s * 1e3, 3),
                "assumed_ici_gbps_per_link": 45,
                "gathered_rows": W * cap,
                "total_ms_bound": round((per_call + ici_s) * 1e3, 3),
            },
            compile_s_sync=round(c_s, 1),
        )
    except Exception as e:  # noqa: BLE001 — bound is additive evidence
        _diag(config=2, sync_term_error=str(e)[:160])


def bench_config3() -> None:
    """Config 3: FID — Inception-v3 forward + streaming moments on device,
    and the compute (Newton–Schulz trace sqrtm on TPU) timed steady-state.

    r5 adds the ABSOLUTE utilization story (VERDICT r4 task 2): per-step
    device time + XLA cost-analysis FLOPs give achieved FLOP/s, reported as
    MFU against the chip's published bf16 peak — for the shipping f32
    extractor and the bf16 compute-dtype path (`InceptionFeatureExtractor
    (dtype=bfloat16)`, the TPU-recommended configuration).
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import FID

    fid = FID(feature=2048, streaming=True)
    batch = 64
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.rand(batch, 3, 299, 299).astype(np.float32))

    state0 = fid.pure_update(fid.init_state(), imgs, True)
    update_step = lambda s: fid.pure_update(s, imgs, True)  # noqa: E731

    per_step = None
    try:
        med, alls, progs, compile_s = _device_step_us(
            {"cfg3_fid_update": update_step}, state0, k=16, execs=8
        )
        per_step = float(med["cfg3_fid_update"]) * 1e-6
        final = progs["cfg3_fid_update"](state0)
        _diag(config=3, method="device-trace,k=16,execs=8",
              update_compile_s=round(compile_s, 1),
              device_ms_per_step=round(float(med["cfg3_fid_update"]) / 1e3, 3))
    except Exception as e:  # noqa: BLE001
        _diag(config=3, device_trace_fallback=str(e)[:200])
        per_step, compile_s, resolution, final = _time_scan_step(
            update_step, state0, k1=4, k2=36
        )
        per_step = max(per_step, resolution)
        _diag(config=3, update_compile_s=round(compile_s, 1))
    final = fid.pure_update(final, imgs, False)

    def perturb(state, i):
        out = dict(state)
        out["real_sum"] = state["real_sum"] + i * 1e-12
        return out

    per_call, compute_compile_s, val = _time_repeat_compute(fid.pure_compute, final, perturb)
    assert np.isfinite(float(np.asarray(val)))
    _diag(config=3, compute_compile_s=round(compute_compile_s, 1))
    _emit("fid_inception_forward", round(batch / per_step, 1), "imgs/s")
    _emit("fid_compute_sqrtm", round(per_call, 3), "s")

    # ---- MFU: bare extractor forward, f32 vs bf16 compute dtype ---------
    try:
        from metrics_tpu.models.inception import InceptionFeatureExtractor

        kind = jax.devices()[0].device_kind
        peak = _peak_flops_bf16(kind, config=3)
        for tag, dtype, b in (
            ("f32", jnp.float32, batch),
            ("bf16", jnp.bfloat16, batch),
            ("bf16_b256", jnp.bfloat16, 256),
        ):
            ext = InceptionFeatureExtractor(feature=2048, dtype=dtype)
            x = jnp.asarray(rng.rand(b, 3, 299, 299).astype(np.float32))

            # imgs ride the scan CARRY, not a closure: a closed-over batch is
            # baked into the program as a constant, and at batch 256 the 274MB
            # payload overflows the remote-compile request (HTTP 413)
            def fwd_step(state, _ext=ext):
                chk, imgs_c = state
                f = _ext(imgs_c + chk * 1e-24)
                return (chk + f.astype(jnp.float32).sum() * 1e-12, imgs_c)

            name = f"cfg3_fwd_{tag}"
            med, alls, progs, c_s = _device_step_us(
                {name: fwd_step}, (jnp.zeros(()), x), k=8, execs=6
            )
            # FLOPs from a single-forward program: cost_analysis of a scanned
            # while-loop may count the body once, so don't divide the scan's
            flops_per_step = _program_flops(jax.jit(lambda y, _e=ext: _e(y)), x)
            step_us = float(med[name])
            achieved = flops_per_step / (step_us * 1e-6) if flops_per_step else None
            mfu = 100.0 * achieved / peak if (achieved and peak) else None
            _diag(config=3, fwd=tag, batch=b, device_kind=kind,
                  device_ms_per_fwd=round(step_us / 1e3, 3),
                  imgs_per_s=round(b / (step_us * 1e-6), 1),
                  gflops_per_fwd=round(flops_per_step / 1e9, 2) if flops_per_step else None,
                  achieved_tflops=round(achieved / 1e12, 2) if achieved else None,
                  peak_bf16_tflops=round(peak / 1e12, 1) if peak else None,
                  compile_s=round(c_s, 1))
            if tag != "f32" and mfu is not None:
                _emit(f"inception_fwd_mfu_{tag}", round(mfu, 1), "percent_of_bf16_peak")
            elif mfu is not None:
                _diag(config=3, f32_mfu_vs_bf16_peak=round(mfu, 1))
    except Exception as e:  # noqa: BLE001 — MFU rows are additive evidence
        _diag(config=3, mfu_error=f"{type(e).__name__}: {e}"[:300])


def bench_config4() -> None:
    """Config 4: BERTScore — in-framework BERT forward as the scoring engine
    (steady-state wall time: tokenization + embedding + greedy match; the
    compute mixes host batching and device programs, so it is timed
    end-to-end with a median over repeats, value fetched to force
    completion)."""
    from metrics_tpu import BERTScore

    sents_per_batch = 64
    bs = BERTScore(max_length=64, batch_size=sents_per_batch)
    preds = ["the quick brown fox jumps over the lazy dog"] * sents_per_batch
    refs = ["a quick brown fox jumped over lazy dogs"] * sents_per_batch
    for _ in range(4):
        bs.update(preds, refs)
    t0 = time.perf_counter()
    out = bs.compute()
    _ = float(np.mean(out["f1"]))
    first = time.perf_counter() - t0
    ts = []
    for _ in range(REPS):
        bs._computed = None
        t0 = time.perf_counter()
        out = bs.compute()
        _ = float(np.mean(out["f1"]))
        ts.append(time.perf_counter() - t0)
    dt = sorted(ts)[len(ts) // 2]
    _diag(config=4, compile_s=round(first - dt, 1))
    _emit("bertscore_compute", round(4 * sents_per_batch / dt, 1), "sentences/s")

    # ---- encoder MFU (VERDICT r4 task 2): device time + cost-analysis ----
    # FLOPs for (a) the DEFAULT BERTScore encoder (tiny: hidden 128 x 4
    # layers — expected low MFU, the matmuls are too small to fill the MXU;
    # that is a model-size roofline fact, not framework overhead) and (b) a
    # BERT-base-shaped encoder in bf16, the realistic heavy-forward shape.
    try:
        import jax
        import jax.numpy as jnp

        from metrics_tpu.models.bert import BertConfig, bert_apply, bert_init

        kind = jax.devices()[0].device_kind
        peak = _peak_flops_bf16(kind, config=4)
        L = 64
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 30000, (sents_per_batch, L)))
        mask = jnp.ones((sents_per_batch, L), jnp.int32)
        shapes = {
            "tiny_default": (BertConfig(), jnp.float32),
            "base_bf16": (
                BertConfig(hidden_size=768, num_hidden_layers=12,
                           num_attention_heads=12, intermediate_size=3072),
                jnp.bfloat16,
            ),
        }
        for tag, (cfg, dtype) in shapes.items():
            params = bert_init(cfg)
            if dtype != jnp.float32:
                params = jax.tree_util.tree_map(
                    lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
                    params,
                )

            # token ids must depend on the loop carry — an invariant encoder
            # body gets hoisted out of the scan by XLA and the per-step time
            # collapses to ~0 (caught in the first r5 capture: 0.0 ms/fwd).
            # params ride the carry, not a closure: closed-over weights are
            # baked into the program (220MB for base-bf16) and overflow the
            # remote-compile request limit (HTTP 413)
            def enc_step(state, _c=cfg):
                i, acc, p = state
                ids_i = (ids + i) % 30000
                hidden = bert_apply(p, ids_i, mask, config=_c)
                return (i + 1, acc + hidden[-1].astype(jnp.float32).sum() * 1e-12, p)

            name = f"cfg4_enc_{tag}"
            med, alls, progs, c_s = _device_step_us(
                {name: enc_step}, (jnp.zeros((), jnp.int32), jnp.zeros(()), params),
                k=8, execs=6,
            )
            flops = _program_flops(
                jax.jit(lambda p, i, m, _c=cfg: bert_apply(p, i, m, config=_c)[-1]),
                params, ids, mask,
            )
            step_us = float(med[name])
            achieved = flops / (step_us * 1e-6) if flops else None
            mfu = 100.0 * achieved / peak if (achieved and peak) else None
            _diag(config=4, encoder=tag, device_kind=kind, seq_len=L,
                  batch=sents_per_batch,
                  device_ms_per_fwd=round(step_us / 1e3, 3),
                  sents_per_s_device=round(sents_per_batch / (step_us * 1e-6), 1),
                  gflops_per_fwd=round(flops / 1e9, 2) if flops else None,
                  achieved_tflops=round(achieved / 1e12, 2) if achieved else None,
                  peak_bf16_tflops=round(peak / 1e12, 1) if peak else None,
                  compile_s=round(c_s, 1))
            if mfu is not None:
                _emit(f"bert_encoder_mfu_{tag}", round(mfu, 1), "percent_of_bf16_peak")
    except Exception as e:  # noqa: BLE001 — MFU rows are additive evidence
        _diag(config=4, mfu_error=f"{type(e).__name__}: {e}"[:300])


def bench_config5() -> None:
    """Config 5: RetrievalMAP + NDCG over ragged query groups (segment ops),
    steady-state, vs the reference's per-query python-loop mechanism in
    torch-CPU (reference ``retrieval/retrieval_metric.py:93-139``)."""
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP, RetrievalNormalizedDCG

    n, queries = 65536, 1024
    rng = np.random.RandomState(0)
    idx_np = rng.randint(0, queries, (n,))
    preds_np = rng.rand(n).astype(np.float32)
    target_np = rng.randint(0, 2, (n,))
    idx, preds, target = jnp.asarray(idx_np), jnp.asarray(preds_np), jnp.asarray(target_np)

    m_map = RetrievalMAP(num_queries=queries)
    m_ndcg = RetrievalNormalizedDCG(num_queries=queries)
    s_map = m_map.pure_update(m_map.init_state(), preds, target, idx)
    s_ndcg = m_ndcg.pure_update(m_ndcg.init_state(), preds, target, idx)

    def both(state_pair):
        a, b = state_pair
        return m_map.pure_compute(a), m_ndcg.pure_compute(b)

    def perturb(state_pair, i):
        a, b = state_pair
        a2 = dict(a)
        # cat-states are lists of per-batch arrays in eager mode
        a2["preds"] = [x + i * 1e-12 for x in a["preds"]]
        return a2, b

    per_call, compile_s, (v1, v2) = _time_repeat_compute(both, (s_map, s_ndcg), perturb)
    assert np.isfinite(float(np.asarray(v1))) and np.isfinite(float(np.asarray(v2)))

    # fused path: one row store, ONE lexsort for both metrics
    from metrics_tpu import RetrievalCollection

    coll = RetrievalCollection(
        {"map": RetrievalMAP(), "ndcg": RetrievalNormalizedDCG()}, num_queries=queries
    )
    s_coll = coll.pure_update(coll.init_state(), preds, target, idx)

    def fused(state):
        return coll.pure_compute(state)

    def perturb_coll(state, i):
        s2 = dict(state)
        s2["preds"] = [x + i * 1e-12 for x in state["preds"]]
        return s2

    per_call_fused, compile_fused, vals = _time_repeat_compute(fused, s_coll, perturb_coll)
    assert np.allclose(float(np.asarray(vals["map"])), float(np.asarray(v1)), atol=1e-6)
    assert np.allclose(float(np.asarray(vals["ndcg"])), float(np.asarray(v2)), atol=1e-6)
    _diag(config=5, fused_ms=round(per_call_fused * 1e3, 2), fused_compile_s=round(compile_fused, 1),
          fused_vs_separate=round(per_call / per_call_fused, 2) if per_call_fused else None)

    # reference mechanism: group rows per query id in python, loop groups
    try:
        import torch

        tp, tt = torch.from_numpy(preds_np), torch.from_numpy(target_np)
        groups = {}
        for i, q in enumerate(idx_np):
            groups.setdefault(int(q), []).append(i)
        t0 = time.perf_counter()
        maps, ndcgs = [], []
        for rows in groups.values():
            ridx = torch.tensor(rows)
            p, t = tp[ridx], tt[ridx]
            order = torch.argsort(p, descending=True)
            rel = t[order].float()
            pos = torch.arange(1, len(rows) + 1, dtype=torch.float32)
            csum = rel.cumsum(0)
            maps.append(float((csum / pos * rel).sum() / rel.sum()) if rel.sum() else 0.0)
            dcg = float((rel / torch.log2(pos + 1)).sum())
            irel = torch.sort(rel, descending=True).values
            idcg = float((irel / torch.log2(pos + 1)).sum())
            ndcgs.append(dcg / idcg if idcg else 0.0)
        base_s = time.perf_counter() - t0
        vs = round(base_s / per_call, 1)
    except Exception:
        vs = None
    _diag(config=5, compile_s=round(compile_s, 1))
    _emit("retrieval_map_ndcg_compute", round(per_call * 1e3, 2), "ms/65536-docs", vs)
    _emit(
        "retrieval_map_ndcg_fused_compute", round(per_call_fused * 1e3, 2), "ms/65536-docs",
        round(base_s / per_call_fused, 1) if vs is not None and per_call_fused else None,
    )


def build_config7_loop():
    """Shared eval-loop builder for config 7 AND scripts/dissect_config7.py.

    The dissection's per-component attribution is only valid while its step
    functions are the SAME computation as the bench's — so both build here.
    Returns dict(make_step, state0, k1, k2, batch, img_px, on_tpu) where
    ``make_step(with_fid, with_acc, with_auroc)`` yields a scan-able step;
    (False,)*3 is the bare forward, (True,)*3 the full metric loop."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import AUROC, Accuracy, FID, MetricCollection

    on_tpu = jax.default_backend() == "tpu"
    batch = 16 if on_tpu else 4
    img_px = 299 if on_tpu else 96  # CPU: keep the conv stack affordable
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.rand(batch, 3, img_px, img_px).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, (batch,)))

    # separate instances: `inception` is the MODEL under evaluation; the FID
    # metric receives its precomputed features (feature=identity), so the
    # overhead number attributes ONLY the moment update to the metric — not
    # a second forward that would otherwise hide inside FID.update unless
    # XLA happened to CSE it
    inception = FID(feature=2048, streaming=True).inception
    fid = FID(feature=lambda f: f, feature_dim=2048, streaming=True)
    head = jnp.asarray(rng.rand(2048, 10).astype(np.float32) * 0.01)

    mc = MetricCollection({"acc": Accuracy(num_classes=10)})
    auroc = AUROC().with_capacity(64 * batch)
    probs_w = jax.nn.softmax(rng.rand(batch, 10).astype(np.float32))
    mc.update(jnp.asarray(probs_w), labels)
    mc.reset()
    auroc.update(jnp.asarray(probs_w[:, 1]), (labels == 1).astype(jnp.int32))
    auroc.reset()

    def _step_inputs(chk):
        # carry-dependent epsilon: numerically nil but makes the forward
        # iteration-dependent, so XLA cannot hoist it out of the scan in
        # EITHER program (hoisting only one corrupts the comparison)
        return imgs + chk * 1e-24

    def make_step(with_fid: bool, with_acc: bool, with_auroc: bool):
        def step(state):
            chk, fid_s, (mc_s, au_s) = state
            x = _step_inputs(chk)
            feats = inception(x)
            logits = feats @ head
            probs = jax.nn.softmax(logits, -1)
            if with_fid:
                fid_s = fid.pure_update(fid_s, feats, True)
            if with_acc:
                mc_s = mc.pure_update(mc_s, probs, labels)
            if with_auroc:
                au_s = auroc.pure_update(au_s, probs[:, 1], (labels == 1).astype(jnp.int32))
            return (chk + logits.sum() * 1e-12, fid_s, (mc_s, au_s))

        return step

    feats0 = inception(imgs)
    fid_s0 = fid.pure_update(fid.init_state(), feats0, True)
    au_s0 = auroc.pure_update(
        auroc.init_state(), jnp.asarray(probs_w[:, 1]), (labels == 1).astype(jnp.int32)
    )
    state0 = (jnp.zeros(()), fid_s0, (mc.init_state(), au_s0))
    k1, k2 = (4, 20) if on_tpu else (2, 6)
    return dict(make_step=make_step, state0=state0, k1=k1, k2=k2,
                batch=batch, img_px=img_px, on_tpu=on_tpu)


def bench_config7() -> None:
    """North star (BASELINE.md): metric overhead < 1% of forward-pass time in
    an eval loop running FID + Accuracy + AUROC together.

    r5 method of record (VERDICT r4 task 1): DEVICE-TIMELINE timing. Both
    programs — model forward only, and model forward + all three metric
    updates fused into the step — are K-step scans executed round-robin
    under one jax.profiler trace; each execution's duration is read from
    the device timeline, which dispatch cost and tunnel drift cannot reach.
    Per-rotation pairing gives an overhead distribution (median + IQR), and
    the whole trace is run TWICE (independent captures) for reproduction.
    The r4 paired-slope wall-clock method stays as a cross-check."""
    cfg = build_config7_loop()
    state0, on_tpu = cfg["state0"], cfg["on_tpu"]
    base_step = cfg["make_step"](False, False, False)
    full_step = cfg["make_step"](True, True, True)

    device_ok = False
    k = 24 if on_tpu else 4
    try:
        runs = []
        for run_idx in (1, 2):
            med, alls, progs, compile_s = _device_step_us(
                {"cfg7_fwd": base_step, "cfg7_full": full_step},
                state0, k=k, execs=10,
            )
            fwd = np.array(alls["cfg7_fwd"])
            full = np.array(alls["cfg7_full"])
            n = min(len(fwd), len(full))
            ov = (full[:n] - fwd[:n]) / fwd[:n] * 100.0  # paired by rotation order
            med_ov = float(np.median(ov))
            p25, p75 = float(np.percentile(ov, 25)), float(np.percentile(ov, 75))
            runs.append(med_ov)
            _diag(config=7, method=f"device-trace,k={k},execs=10,run={run_idx}",
                  fwd_device_ms=round(float(med["cfg7_fwd"]) / 1e3, 4),
                  with_metrics_device_ms=round(float(med["cfg7_full"]) / 1e3, 4),
                  overhead_pct=round(med_ov, 3),
                  overhead_iqr=[round(p25, 3), round(p75, 3)],
                  below_noise_floor=bool(p25 <= 0.0 <= p75),
                  compile_s=round(compile_s, 1))
        device_ok = True
        overhead_pct = float(np.median(runs))
    except Exception as e:  # noqa: BLE001
        _diag(config=7, device_trace_fallback=str(e)[:200])

    # wall-clock cross-check (r4 method of record); primary when no device
    # timeline exists
    k1, k2 = (4, 28) if on_tpu else (2, 6)
    (base_s, full_s), compile_s, overheads = _paired_slope_pair(
        base_step, full_step, state0,
        k1=k1, k2=k2, reps=(12 if device_ok else 20) if on_tpu else 3,
    )
    ov = np.array(overheads) * 100.0
    wall_pct = float(np.median(ov)) if ov.size else 0.0
    p25 = float(np.percentile(ov, 25)) if ov.size else 0.0
    p75 = float(np.percentile(ov, 75)) if ov.size else 0.0
    _diag(config=7, fwd_ms=round(base_s * 1e3, 3),
          with_metrics_ms=round(full_s * 1e3, 3),
          overhead_pct=round(wall_pct, 2), compile_s=round(compile_s, 1),
          method=f"paired-slope,k={k1}->{k2},reps={len(overheads)}"
                 + (",cross-check" if device_ok else ""),
          overhead_iqr=[round(p25, 2), round(p75, 2)],
          # an IQR straddling zero means the median sits inside rep noise
          below_noise_floor=bool(p25 <= 0.0 <= p75))
    if not device_ok:
        overhead_pct = wall_pct
    overhead_pct = max(overhead_pct, 0.0)
    if not on_tpu:
        # the target is defined against an ACCELERATOR forward pass
        # (BASELINE.md: v4-class eval loop); on the scaled-down CPU stand-in
        # the fixed 2048^2 FID moment update dwarfs the tiny forward, so the
        # ratio would misrepresent the design. Record diagnostics only.
        _diag(config=7, note="overhead ratio only meaningful vs an accelerator forward; skipped on cpu")
        return
    _emit("metric_overhead_vs_forward", round(overhead_pct, 2), "percent")


def bench_config6() -> None:
    """Config 6: binned PR-curve stat mechanisms on hardware, PAIRED.

    Three bit-identical mechanisms for the same [C, T] counts: fused-XLA
    compare (the TPU default), the opt-in pallas kernel, and the
    bucket-histogram path (the off-TPU default). Methodology note (r4): the
    small-K slope method produced 10-30x run-to-run swings for these sub-ms
    programs even interleaved; K=32-amortized back-to-back PAIRED timing
    with per-pair ratio medians is stable (IQR within a few percent) and is
    what this config records."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from metrics_tpu.ops.pallas_binned import binned_stat_scores

    n, c, t = 65536, 8, 128
    K = 32
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(n, c).astype(np.float32))
    target = jnp.asarray((rng.rand(n, c) > 0.5).astype(np.int32))
    thresholds = jnp.linspace(0.0, 1.0, t)

    on_tpu = jax.default_backend() == "tpu"
    mechanisms = [("xla", False), ("bucket", None)] if not on_tpu else [
        ("xla", False), ("pallas", True), ("bucket", None)]
    # "bucket" must time the real mechanism, not the backend dispatch (which
    # would pick xla on TPU): call the path directly
    from metrics_tpu.ops.pallas_binned import _binned_stats_bucket

    def make(name, flag):
        @jax.jit
        def run(p):
            def body(acc, i):
                if name == "bucket":
                    out = _binned_stats_bucket(p + i * 1e-9, target, thresholds)
                else:
                    out = binned_stat_scores(p + i * 1e-9, target, thresholds, use_pallas=flag)
                return acc + sum(jnp.sum(x) for x in out), None

            return lax.scan(body, jnp.asarray(0.0), jnp.arange(K))[0]

        return run

    runs = {}
    outputs = {}
    compile_s = 0.0
    for name, flag in mechanisms:
        try:
            outputs[name] = jax.tree_util.tree_leaves(jax.jit(
                lambda p, flag=flag, name=name: (
                    _binned_stats_bucket(p, target, thresholds) if name == "bucket"
                    else binned_stat_scores(p, target, thresholds, use_pallas=flag))
            )(preds))
            fn = make(name, flag)
            t0 = time.perf_counter()
            _ = float(fn(preds))
            compile_s += time.perf_counter() - t0
            runs[name] = fn
        except Exception as e:  # pallas may be unsupported on this chip rev
            _diag(config=6, path=name, error=str(e)[:200])
    _diag(config=6, compile_s=round(compile_s, 1))

    # hardware parity evidence: every mechanism's compiled output must agree
    # bit-for-bit (VERDICT r2 item 2; the bucket path promises bit-exactness)
    names = [nm for nm, _ in mechanisms if nm in runs]
    for other in names[1:]:
        max_diff = max(
            float(jnp.max(jnp.abs(a - b)))
            for a, b in zip(outputs[names[0]], outputs[other])
        )
        _diag(config=6, **{f"{other}_vs_{names[0]}_max_abs_diff": max_diff})
        if max_diff > 0:
            _diag(config=6, parity=f"FAILED — {other} diverges from {names[0]} on hardware")

    times = {nm: [] for nm in names}
    for _ in range(20):
        for nm in names:  # back-to-back within each rep: drift hits all alike
            t0 = time.perf_counter()
            _ = float(runs[nm](preds))
            times[nm].append((time.perf_counter() - t0) / K)
    results = {}
    for nm in names:
        results[nm] = float(np.median(times[nm]))
        _diag(config=6, path=nm, per_call_ms=round(results[nm] * 1e3, 3))
    if "pallas" in results and "xla" in results:
        ratio = np.array(times["xla"]) / np.array(times["pallas"])
        _diag(config=6, xla_over_pallas_ratio_med=round(float(np.median(ratio)), 2),
              p25=round(float(np.percentile(ratio, 25)), 2),
              p75=round(float(np.percentile(ratio, 75)), 2))

    default_mech = "xla" if on_tpu else "bucket"
    if default_mech in results:
        # headline row: the DEFAULT-dispatch mechanism for this backend;
        # vs = how much faster it is than the worst credible alternative
        other = "bucket" if on_tpu else "xla"
        vs = round(results[other] / results[default_mech], 2) if other in results else None
        _emit(
            f"binned_pr_stats_65k_rows_{default_mech}",
            round(results[default_mech] * 1e3, 3), "ms", vs,
        )


def bench_config8() -> None:
    """Config 8: host-sync collective fusion — fused vs per-leaf counts.

    The ISSUE-2 acceptance measurement: a MetricCollection of ≥3 metrics /
    ≥6 state leaves host-syncs through the bucketed planner
    (`parallel/bucketing.py`) and through the per-leaf path, with the bare
    collective seam (`_raw_process_allgather`) replaced by a counting echo
    gather at a simulated W=8 world — the counts and payload shapes are the
    real protocol's, only the transport is faked (multi-chip hardware is
    unavailable; same split as config 2's sync-term bound). Emits the fused
    collective count with `vs_baseline` = per-leaf/fused ratio, plus a W=8
    sync-term *bound*: collectives × per-collective launch floor + payload
    bytes over DCN (host gathers ride the data-center network, not ICI —
    1 ms/collective launch floor and 3 GB/s are the conservative knobs,
    both reported in the diagnostic for re-derivation).

    Asserts (CI gates contract) that the fused path issues FEWER collectives
    than the collection has leaves, and no more than 1 header + one per
    dtype/fx bucket.
    """
    import jax
    import jax.numpy as jnp

    import metrics_tpu.parallel.sync as sync_mod
    from metrics_tpu.core.collections import MetricCollection
    from metrics_tpu.core.metric import Metric
    from metrics_tpu.parallel.bucketing import build_sync_plan, clear_sync_plan_cache

    W = 8

    class _CountingEcho:
        """W-rank echo gather: every peer contributes this rank's payload."""

        def __init__(self):
            self.calls = 0
            self.bytes = 0

        def __call__(self, x):
            self.calls += 1
            row = np.asarray(x)
            self.bytes += row.nbytes * W
            return jnp.asarray(np.stack([row.copy() for _ in range(W)]))

    class _Avg(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

        def update(self, x):
            self.total = self.total + jnp.sum(x)
            self.count = self.count + jnp.asarray(jnp.size(x), jnp.int32)

        def compute(self):
            return self.total / self.count

    class _Extrema(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("mx", jnp.full((NUM_CLASSES,), -jnp.inf), dist_reduce_fx="max")
            self.add_state("mn", jnp.full((NUM_CLASSES,), jnp.inf), dist_reduce_fx="min")

        def update(self, x):
            self.mx = jnp.maximum(self.mx, x)
            self.mn = jnp.minimum(self.mn, x)

        def compute(self):
            return self.mx - self.mn

    class _Hist(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("hist", jnp.zeros((32,), jnp.int32), dist_reduce_fx="sum")
            self.add_state("seen", jnp.zeros(()), dist_reduce_fx="sum")

        def update(self, x):
            self.hist = self.hist + jnp.histogram(x, bins=32, range=(0.0, 1.0))[0].astype(jnp.int32)
            self.seen = self.seen + 1.0

        def compute(self):
            return self.hist

    class _Curve(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")

        def update(self, p, t):
            self.preds.append(p)
            self.target.append(t)

        def compute(self):
            return jnp.concatenate(self.preds)

    rng = np.random.RandomState(0)

    def make_states():
        mc = MetricCollection(
            {"avg": _Avg(), "ext": _Extrema(), "hist": _Hist(), "curve": _Curve()}
        )
        x = jnp.asarray(rng.rand(256).astype(np.float32))
        mc["avg"].update(x)
        mc["ext"].update(jnp.asarray(rng.rand(NUM_CLASSES).astype(np.float32)))
        mc["hist"].update(x)
        mc["curve"].update(x[:100], jnp.asarray(rng.randint(0, 2, 100), jnp.int32))
        combined, reds = {}, {}
        for key, m in mc.items():
            for name, v in m._state.items():
                combined[f"{key}.{name}"] = v
                reds[f"{key}.{name}"] = m._reductions.get(name)
        return mc, combined, reds

    saved_count, saved_seam = jax.process_count, sync_mod._raw_process_allgather
    try:
        jax.process_count = lambda: W
        counts = {}
        for mode in ("fused", "per_leaf"):
            clear_sync_plan_cache()
            echo = _CountingEcho()
            sync_mod._raw_process_allgather = echo
            _mc, combined, reds = make_states()
            sync_mod.host_sync_state(combined, reds, update_count=1, timeout=0,
                                     fused=(mode == "fused"))
            counts[mode] = {"collectives": echo.calls, "bytes": echo.bytes}
        plan = build_sync_plan(combined, reds)
        n_leaves = len(combined)
    finally:
        jax.process_count = saved_count
        sync_mod._raw_process_allgather = saved_seam
        clear_sync_plan_cache()

    fused_n = counts["fused"]["collectives"]
    leaf_n = counts["per_leaf"]["collectives"]
    # the CI gates contract: fusion must beat one-collective-per-leaf and
    # stay within the planner's 1 header + one-per-bucket budget
    assert fused_n < n_leaves, f"fused path issued {fused_n} >= leaves {n_leaves}"
    assert fused_n <= 1 + plan.n_buckets, (fused_n, plan.n_buckets)

    # W=8 sync-term bound: host collectives ride DCN with a per-collective
    # launch floor that dominates small metric payloads — which is exactly
    # why collective COUNT is the lever this config measures. The knobs are
    # env-overridable so site operators can re-derive the bound for their
    # own fabric without editing the bench.
    launch_ms = float(os.environ.get("METRICS_TPU_BENCH_LAUNCH_MS", "1.0"))
    dcn_gbps = float(os.environ.get("METRICS_TPU_BENCH_DCN_GBPS", "3.0"))
    intra_launch_ms = float(os.environ.get("METRICS_TPU_BENCH_INTRA_LAUNCH_MS", "0.1"))
    bound = {
        mode: round(c["collectives"] * launch_ms + c["bytes"] / (dcn_gbps * 1e9) * 1e3, 3)
        for mode, c in counts.items()
    }
    # Tiered two-term bound (the hierarchical schedule of ISSUE 20): with a
    # tier map of size TIER the slow-wire traffic shrinks by
    # (n_tiers-1)/(W-1) — each payload crosses DCN once per inter-tier peer
    # instead of once per world peer — while two extra fast hops per bucket
    # ride the intra-tier wire at its (much lower) launch floor.
    TIER = 4
    n_tiers = W // TIER
    tiered_bound = {}
    for mode, c in counts.items():
        inter_bytes = c["bytes"] * (n_tiers - 1) / (W - 1)
        intra_ms = c["collectives"] * 2 * intra_launch_ms
        inter_ms = c["collectives"] * launch_ms + inter_bytes / (dcn_gbps * 1e9) * 1e3
        tiered_bound[mode] = {
            "intra_ms": round(intra_ms, 3),
            "inter_ms": round(inter_ms, 3),
            "total_ms": round(intra_ms + inter_ms, 3),
        }
    _diag(
        config=8,
        world=W,
        leaves=n_leaves,
        buckets=plan.n_buckets,
        per_leaf_collectives=leaf_n,
        fused_collectives=fused_n,
        payload_bytes={m: c["bytes"] for m, c in counts.items()},
        sync_term_w8_ms_bound=bound,
        tiered_sync_term_w8_ms_bound={"tier_size": TIER, **tiered_bound},
        assumed={
            "launch_ms_per_collective": launch_ms,
            "dcn_gbps": dcn_gbps,
            "intra_launch_ms_per_collective": intra_launch_ms,
        },
    )
    _emit("fused_sync_collectives", fused_n, "collectives/sync",
          round(leaf_n / fused_n, 3))


def bench_config9() -> None:
    """Config 9: compute-group dedup — grouped vs ungrouped collection cost.

    The ISSUE-3 acceptance measurement: a 4-metric stat-score collection
    (Precision / Recall / F1 / Specificity, equal args — one compute group)
    is driven through `update` with compute groups on and off, counting
    `_stat_scores_update` dispatches and timing the eager per-step update
    wall clock, then host-synced through the fused planner at a simulated
    W=8 world (config 8's counting-echo seam) to account collectives and
    payload bytes. Asserts (CI gates contract):

    - grouped update dispatches ≤ ungrouped / 2 (a 4-member group runs ONE
      stat-score update per step — a 4x dispatch reduction);
    - grouped fused-sync payload bytes strictly below ungrouped (one
      gathered tp/fp/tn/fn quartet instead of four), with no more
      collectives.

    Emits `collection_update_us_per_step` (grouped) with `vs_baseline` =
    ungrouped/grouped wall-clock ratio; the dispatch counts, payload bytes
    and header column usage ride the diagnostic line.
    """
    import jax
    import jax.numpy as jnp

    import metrics_tpu.classification.stat_scores as stat_scores_mod
    import metrics_tpu.parallel.sync as sync_mod
    from metrics_tpu import F1, Precision, Recall, Specificity
    from metrics_tpu.core.collections import MetricCollection
    from metrics_tpu.parallel.bucketing import clear_sync_plan_cache

    W = 8
    STEPS = 30

    class _CountingEcho:
        def __init__(self):
            self.calls = 0
            self.bytes = 0

        def __call__(self, x):
            self.calls += 1
            row = np.asarray(x)
            self.bytes += row.nbytes * W
            return jnp.asarray(np.stack([row.copy() for _ in range(W)]))

    def make(grouped: bool) -> MetricCollection:
        mc = MetricCollection(
            {
                "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
                "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
                "f1": F1(num_classes=NUM_CLASSES, average="macro"),
                "spec": Specificity(num_classes=NUM_CLASSES, average="macro"),
            },
            compute_groups=grouped,
        )
        for m in mc.values():
            # config 9 measures the EAGER grouped-vs-ungrouped dedup; under
            # the compiled hot path (config 11's subject) the traced update
            # is cached, so the _stat_scores_update counter below would
            # count traces, not per-step dispatches
            m.compiled_update = False
        return mc

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (BATCH,)))

    dispatches = {}
    orig_update = stat_scores_mod._stat_scores_update
    counter = {"n": 0}

    def counting(*args, **kwargs):
        counter["n"] += 1
        return orig_update(*args, **kwargs)

    step_us = {}
    stat_scores_mod._stat_scores_update = counting
    try:
        for mode in ("grouped", "ungrouped"):
            mc = make(mode == "grouped")
            mc.update(preds, target)  # warm: group planning + jit compile
            counter["n"] = 0
            jax.block_until_ready(mc["prec"]._state["tp"])
            t0 = time.perf_counter()
            for _ in range(STEPS):
                mc.update(preds, target)
            jax.block_until_ready(mc["prec"]._state["tp"])
            step_us[mode] = (time.perf_counter() - t0) / STEPS * 1e6
            dispatches[mode] = counter["n"] / STEPS
    finally:
        stat_scores_mod._stat_scores_update = orig_update

    # per-step dispatch dedup: the 4-member group must run ONE update
    assert dispatches["grouped"] * 2 <= dispatches["ungrouped"], dispatches

    saved_count, saved_seam = jax.process_count, sync_mod._raw_process_allgather
    sync_counts = {}
    try:
        jax.process_count = lambda: W
        for mode in ("grouped", "ungrouped"):
            clear_sync_plan_cache()
            echo = _CountingEcho()
            sync_mod._raw_process_allgather = echo
            mc = make(mode == "grouped")
            mc.update(preds, target)
            mc.sync(timeout=0)
            mc.unsync()
            # unique states the combined fused plan carried (header columns)
            n_keys = sum(len(m._state) for _k, m, _p in mc._sync_state_owners())
            sync_counts[mode] = {"collectives": echo.calls, "bytes": echo.bytes, "state_keys": n_keys}
    finally:
        jax.process_count = saved_count
        sync_mod._raw_process_allgather = saved_seam
        clear_sync_plan_cache()

    # sync dedup: strictly fewer payload bytes, no more collectives, and a
    # 4x smaller combined header (4 unique state keys instead of 16)
    assert sync_counts["grouped"]["bytes"] < sync_counts["ungrouped"]["bytes"], sync_counts
    assert sync_counts["grouped"]["collectives"] <= sync_counts["ungrouped"]["collectives"], sync_counts
    assert sync_counts["grouped"]["state_keys"] < sync_counts["ungrouped"]["state_keys"], sync_counts

    _diag(
        config=9,
        world=W,
        members=4,
        update_dispatches_per_step=dispatches,
        update_us_per_step={m: round(v, 2) for m, v in step_us.items()},
        fused_sync={m: dict(c) for m, c in sync_counts.items()},
    )
    _emit(
        "collection_update_us_per_step",
        round(step_us["grouped"], 2),
        "us/step",
        round(step_us["ungrouped"] / step_us["grouped"], 3),
    )


def bench_config10() -> None:
    """Config 10: preemption-safe checkpoint — snapshot/restore latency +
    restore-after-kill correctness.

    The ISSUE-4 acceptance measurement: a 4-metric curve collection
    (ROC / PrecisionRecallCurve / AveragePrecision / AUROC, one compute
    group for the first three) with large CatBuffers (2^17 rows each
    buffer) is driven through half its batches, snapshotted with
    `save_checkpoint` (timed over REPS saves), then a kill is simulated —
    a leftover temp file plus an incomplete newer step — and a FRESH
    collection restores with `load_checkpoint` (timed) and finishes the
    remaining batches. Asserts (CI gates contract):

    - the loader ignores the kill debris and resumes the newest COMPLETE
      snapshot;
    - every computed value of the resumed run equals the uninterrupted
      run bit for bit (np.array_equal over the full curve outputs).

    Emits `checkpoint_restore_ms` with `vs_baseline` = save/restore ratio;
    snapshot latency, shard size and per-state byte counts ride the
    diagnostic line.
    """
    import jax.numpy as jnp

    from metrics_tpu import (
        AUROC,
        AveragePrecision,
        MetricCollection,
        PrecisionRecallCurve,
        ROC,
        load_checkpoint,
        save_checkpoint,
    )

    CAPACITY = 1 << 17
    N_BATCH, BATCH_ROWS = 16, 4096  # 65536 rows accumulated per metric
    SPLIT = N_BATCH // 2

    rng = np.random.RandomState(10)
    preds = rng.rand(N_BATCH, BATCH_ROWS).astype(np.float32)
    target = rng.randint(0, 2, (N_BATCH, BATCH_ROWS))

    def make() -> "MetricCollection":
        return MetricCollection(
            {
                "roc": ROC().with_capacity(CAPACITY),
                "prc": PrecisionRecallCurve().with_capacity(CAPACITY),
                "ap": AveragePrecision().with_capacity(CAPACITY),
                "auroc": AUROC().with_capacity(CAPACITY),
            }
        )

    def feed(mc, lo, hi):
        for i in range(lo, hi):
            mc.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        return mc

    def flatten(vals):
        out = {}
        for k, v in vals.items():
            leaves = v if isinstance(v, (tuple, list)) else [v]
            out[k] = [np.asarray(x) for x in leaves]
        return out

    ckpt_dir = tempfile.mkdtemp(prefix="metrics_tpu_bench10_")
    try:
        mc = feed(make(), 0, SPLIT)
        n_groups = len(mc.compute_group_keys)
        # snapshot latency (REPS saves into successive steps)
        t0 = time.perf_counter()
        for rep in range(REPS):
            path = save_checkpoint(mc, ckpt_dir, step=rep, rank=0, world=1)
        snapshot_ms = (time.perf_counter() - t0) / REPS * 1e3
        shard_bytes = os.path.getsize(path)

        # simulated kill -9 AFTER the last good snapshot: a half-written
        # temp file plus an incomplete newer step directory
        debris_dir = os.path.join(ckpt_dir, f"step_{REPS:010d}")
        os.makedirs(debris_dir)
        with open(os.path.join(debris_dir, ".tmp-killed.mtck"), "wb") as f:
            f.write(b"\x00" * 4096)

        fresh = make()
        t0 = time.perf_counter()
        load_checkpoint(fresh, ckpt_dir, rank=0, world=1)
        restore_ms = (time.perf_counter() - t0) * 1e3

        resumed_vals = flatten(feed(fresh, SPLIT, N_BATCH).compute())
        uninterrupted_vals = flatten(feed(make(), 0, N_BATCH).compute())
        for k, leaves in uninterrupted_vals.items():
            assert len(resumed_vals[k]) == len(leaves), k
            for got, want in zip(resumed_vals[k], leaves):
                assert np.array_equal(got, want), f"restore-after-kill diverged on {k}"
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    _diag(
        config=10,
        members=4,
        compute_groups=n_groups,
        capacity=CAPACITY,
        rows_at_snapshot=SPLIT * BATCH_ROWS,
        shard_bytes=shard_bytes,
        snapshot_ms=round(snapshot_ms, 2),
        restore_ms=round(restore_ms, 2),
        restore_equals_uninterrupted=True,
    )
    _emit(
        "checkpoint_restore_ms",
        round(restore_ms, 2),
        "ms",
        round(snapshot_ms / restore_ms, 3) if restore_ms else None,
    )


def bench_config11() -> None:
    """Config 11: compiled eager hot path — compiled vs eager step time,
    dispatch counts, and compiled ≡ eager bit-equality.

    The ISSUE-5 acceptance measurement: the torchmetrics-style eager
    ``update()`` surface auto-JITs into ONE donated-state XLA program per
    step (`core/compiled.py`). A 4-metric stat-score collection
    (Precision/Recall/F1/Specificity — one compute group) runs the same
    batch stream with the compiled path pinned ON and pinned OFF, timing
    the per-step wall clock and counting compiled dispatches via
    `compile_stats()`. A CatBuffer curve collection (ROC/PRC/AP — the
    declared side-effect-latch family) exercises the permanent fallback
    path, and a fallback-triggering member (Accuracy) joins a mixed
    collection to show the fused program shrinking around it. Asserts
    (CI gates contract):

    - compiled ≡ eager bit-identical state leaves and compute values on
      every collection above (including the fallback and mixed ones);
    - exactly 1 compiled dispatch per step for the grouped stat-score
      collection AND for the ungrouped one (the collection-level fused
      program covers all 4 members);
    - the curve family records a fallback reason and issues 0 compiled
      dispatches (graceful, silent-by-design fallback);
    - compiled step time ≥ 10x faster than the eager baseline (CPU).

    Emits `compiled_eager_step_us` with `vs_baseline` = eager/compiled;
    dispatch counts, traces and fallback reasons ride the diagnostic line.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import (
        Accuracy,
        AveragePrecision,
        F1,
        Precision,
        PrecisionRecallCurve,
        Recall,
        ROC,
        Specificity,
    )
    from metrics_tpu.core.collections import MetricCollection

    B, STEPS, EQ_STEPS = 256, 30, 8
    rng = np.random.RandomState(11)
    preds = [jnp.asarray(rng.rand(B, NUM_CLASSES).astype(np.float32)) for _ in range(EQ_STEPS)]
    target = [jnp.asarray(rng.randint(0, NUM_CLASSES, (B,))) for _ in range(EQ_STEPS)]

    def make_stats(compiled, grouped=True) -> MetricCollection:
        mc = MetricCollection(
            {
                "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
                "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
                "f1": F1(num_classes=NUM_CLASSES, average="macro"),
                "spec": Specificity(num_classes=NUM_CLASSES, average="macro"),
            },
            compute_groups=grouped,
        )
        for m in mc.values():
            m.compiled_update = compiled  # True = engage immediately (skip warm-up)
        return mc

    def total_dispatches(mc) -> int:
        cs = mc.compile_stats()
        return cs["collection"]["dispatches"] + sum(
            s["dispatches"] for s in cs["members"].values()
        )

    def assert_equal(a, b, what) -> None:
        for (k, ma), mb in zip(a.items(), b.values()):
            for name in ma._state:
                la = jax.tree_util.tree_leaves(ma._state[name])
                lb = jax.tree_util.tree_leaves(mb._state[name])
                assert len(la) == len(lb) and all(
                    np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
                ), f"{what}: state {k}.{name} diverged compiled vs eager"
        va, vb = a.compute(), b.compute()
        for k in va:
            la = jax.tree_util.tree_leaves(va[k])
            lb = jax.tree_util.tree_leaves(vb[k])
            assert len(la) == len(lb) and all(
                np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
            ), f"{what}: value {k} diverged"

    # ---- equality matrix: grouped + ungrouped stat-score collections ----
    for grouped in (True, False):
        eager, compiled = make_stats(False, grouped), make_stats(True, grouped)
        for i in range(EQ_STEPS):
            eager.update(preds[i], target[i])
            compiled.update(preds[i], target[i])
        assert_equal(eager, compiled, f"stat-scores grouped={grouped}")
        if not grouped:
            # collection-level fused program: 4 members, ONE dispatch per step
            cs = compiled.compile_stats()
            per_step = cs["collection"]["dispatches"] / EQ_STEPS
            assert per_step == 1.0, f"ungrouped fused dispatches/step {per_step} != 1"
            assert all(s["dispatches"] == 0 for s in cs["members"].values()), cs

    # ---- fallback family: CatBuffer curve collection ----
    def make_curves(compiled) -> MetricCollection:
        mc = MetricCollection(
            {
                "roc": ROC().with_capacity(B * EQ_STEPS),
                "prc": PrecisionRecallCurve().with_capacity(B * EQ_STEPS),
                "ap": AveragePrecision().with_capacity(B * EQ_STEPS),
            }
        )
        for m in mc.values():
            m.compiled_update = compiled
        return mc

    eager_c, compiled_c = make_curves(False), make_curves(True)
    bp = [jnp.asarray(rng.rand(B).astype(np.float32)) for _ in range(EQ_STEPS)]
    bt = [jnp.asarray(rng.randint(0, 2, (B,))) for _ in range(EQ_STEPS)]
    for i in range(EQ_STEPS):
        eager_c.update(bp[i], bt[i])
        compiled_c.update(bp[i], bt[i])
    assert_equal(eager_c, compiled_c, "curve collection")
    ccs = compiled_c.compile_stats()
    assert total_dispatches(compiled_c) == 0, "fallback family must issue 0 compiled dispatches"
    fallbacks = {
        k: s["fallback"]["update"]
        for k, s in ccs["members"].items()
        if s["fallback"] and "update" in s["fallback"]
    }
    assert fallbacks, "curve collection recorded no fallback reason"

    # ---- fallback-triggering member joining the collection ----
    def make_mixed(compiled) -> MetricCollection:
        mc = MetricCollection(
            {
                "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
                "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
                "acc": Accuracy(num_classes=NUM_CLASSES),
            },
            compute_groups=False,
        )
        for m in mc.values():
            m.compiled_update = compiled
        return mc

    eager_m, compiled_m = make_mixed(False), make_mixed(True)
    for i in range(EQ_STEPS):
        eager_m.update(preds[i], target[i])
        compiled_m.update(preds[i], target[i])
    assert_equal(eager_m, compiled_m, "mixed collection with fallback member")
    mcs = compiled_m.compile_stats()
    assert mcs["members"]["acc"]["fallback"], "Accuracy should fall back (mode latch)"
    assert mcs["collection"]["dispatches"] == EQ_STEPS, mcs["collection"]

    # ---- step time + dispatch accounting (the headline numbers) ----
    step_us = {}
    disp_per_step = None
    for mode in ("compiled", "eager"):
        mc = make_stats(mode == "compiled")
        mc.update(preds[0], target[0])  # warm: group plan (+ trace for compiled)
        base = total_dispatches(mc)
        jax.block_until_ready(mc["prec"]._state["tp"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            mc.update(preds[0], target[0])
        jax.block_until_ready(mc["prec"]._state["tp"])
        step_us[mode] = (time.perf_counter() - t0) / STEPS * 1e6
        if mode == "compiled":
            disp_per_step = (total_dispatches(mc) - base) / STEPS
            stats_compiled = mc.compile_stats()

    assert disp_per_step == 1.0, f"compiled dispatches/step {disp_per_step} != 1"
    speedup = step_us["eager"] / step_us["compiled"]
    assert speedup >= 10.0, (
        f"compiled eager step only {speedup:.1f}x faster than eager "
        f"({step_us['compiled']:.1f} vs {step_us['eager']:.1f} us/step); contract is >= 10x"
    )

    _diag(
        config=11,
        members=4,
        batch=B,
        step_us={m: round(v, 2) for m, v in step_us.items()},
        compiled_dispatches_per_step=disp_per_step,
        compiled_stats={
            "collection": stats_compiled["collection"],
            "leader": stats_compiled["members"]["f1"],
        },
        curve_fallback_reasons={k: v[:80] for k, v in fallbacks.items()},
        equality="bit-identical (grouped, ungrouped, curve-fallback, mixed)",
    )
    _emit(
        "compiled_eager_step_us",
        round(step_us["compiled"], 2),
        "us/step",
        round(speedup, 3),
    )


def bench_config12() -> None:
    """Config 12: async overlapped sync — overlapped vs blocking
    compute()-every-N step-loop wall-clock + bit-identical resolved values.

    The ISSUE-7 acceptance measurement: a sum-state metric runs the same
    update stream at simulated W=8 over the FleetWorld threads harness
    (per-rank background executor lanes, rendezvous collectives riding the
    fleet's per-tier latency model — a full-world gather spans tiers, so
    every collective pays the inter-tier ring delay ``(W-1) x hop``, the
    principled form of the flat 3 ms injection this config used to hard
    code — plus per-step simulated train work) in two modes: blocking
    ``compute()`` every K steps (the gather stalls the step loop) and
    ``sync_mode="overlap"`` (each compute resolves the round launched one
    interval earlier and relaunches — the collective rides behind the K
    steps of work). Asserts (CI gates contract):

    - the overlapped step loop's wall-clock is strictly below blocking
      (the collective is genuinely off the critical path);
    - every overlapped resolve is **bit-identical** to the blocking sync of
      the same update stream one interval earlier (staleness_policy
      "snapshot": the consistent world cut, equal on every rank);
    - both modes issue the SAME number of collective rounds — overlap moves
      the same bytes, it just stops paying for them in step time;
    - ``sync_stats()`` attributes the saving (``overlap_saved_s`` > 0).

    Emits the overlapped/blocking wall-clock ratio with the per-knob
    delays in the diagnostic for re-derivation.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    import metrics_tpu.parallel.async_sync as async_mod
    import metrics_tpu.parallel.sync as sync_mod
    from metrics_tpu.core.metric import Metric
    from metrics_tpu.parallel.bucketing import clear_sync_plan_cache
    from tests.helpers.fake_world import FaultProfile, FleetWorld

    W = 8
    K_STEPS = 5  # train steps per compute interval
    INTERVALS = 8
    STEP_S = 0.002  # simulated per-step train work
    TIER = 4  # fleet latency model: two tiers of four ranks
    INTER_HOP_S = 0.0004  # per ring hop on the slow wire; a full-world
    GATHER_S = INTER_HOP_S * (W - 1)  # gather spans tiers -> (W-1) hops ~ 2.8 ms

    class _Sum(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def update(self, x):
            self.total = self.total + jnp.sum(x)

        def compute(self):
            return self.total

    def run_mode(overlap: bool):
        # the fleet's latency model injects the DCN delay: every full-world
        # gather spans both tiers, so each collective pays (W-1) inter-tier
        # ring hops — the generalized form of a flat per-collective sleep
        world = FleetWorld(
            W,
            FaultProfile(
                tier_size=TIER,
                intra_tier_latency_s=INTER_HOP_S / 20,
                inter_tier_latency_s=INTER_HOP_S,
            ),
        )
        saved = (
            jax.process_count,
            sync_mod._raw_process_allgather,
            async_mod._get_executor,
            async_mod._current_domain,
        )
        values = [[] for _ in range(W)]
        stats = [None] * W
        clear_sync_plan_cache()
        try:
            jax.process_count = lambda: W
            sync_mod._raw_process_allgather = world.allgather
            async_mod._get_executor = world.executor_for_current_rank
            async_mod._current_domain = world.rank_domain

            def body(rank):
                m = _Sum(
                    sync_timeout=0,
                    sync_mode="overlap" if overlap else "blocking",
                    compiled_update=False,  # measure the sync path, not compile time
                )
                m.distributed_available_fn = lambda: True
                t0 = _time.perf_counter()
                for _ in range(INTERVALS):
                    for _step in range(K_STEPS):
                        _time.sleep(STEP_S)  # the "training step"
                        m.update(jnp.asarray([float(rank + 1)]))
                    values[rank].append(np.asarray(m.compute()).copy())
                if m.__dict__.get("_inflight") is not None:
                    m.unsync()  # drain the pipeline's tail round
                elapsed = _time.perf_counter() - t0
                stats[rank] = m.sync_stats()
                return elapsed

            elapsed = world.run(body, timeout=300.0)
        finally:
            (
                jax.process_count,
                sync_mod._raw_process_allgather,
                async_mod._get_executor,
                async_mod._current_domain,
            ) = saved
            world.shutdown_executors()
            clear_sync_plan_cache()
        return max(elapsed), values, world.calls, stats

    wall_block, vals_block, calls_block, _ = run_mode(overlap=False)
    wall_over, vals_over, calls_over, stats_over = run_mode(overlap=True)

    # bit-identity: overlapped interval j serves the blocking world cut of
    # interval j-1 (interval 0 is the documented local-only serve)
    for rank in range(W):
        for j in range(1, INTERVALS):
            assert vals_over[rank][j].tobytes() == vals_block[rank][j - 1].tobytes(), (
                rank, j, vals_over[rank][j], vals_block[rank][j - 1],
            )
    # the overlap moved the same collectives (same rounds, same bytes) —
    # they just stopped stalling the step loop
    assert calls_over == calls_block, (calls_over, calls_block)
    assert wall_over < wall_block, (
        f"overlapped step loop {wall_over * 1e3:.1f} ms not faster than "
        f"blocking {wall_block * 1e3:.1f} ms"
    )
    saved_s = max(s["overlap_saved_s"] for s in stats_over)
    assert saved_s > 0.0, stats_over[0]

    _diag(
        config=12,
        world=W,
        intervals=INTERVALS,
        steps_per_interval=K_STEPS,
        step_ms=STEP_S * 1e3,
        gather_ms=GATHER_S * 1e3,
        blocking_wall_ms=round(wall_block * 1e3, 2),
        overlapped_wall_ms=round(wall_over * 1e3, 2),
        collective_rounds={"blocking": calls_block, "overlapped": calls_over},
        resolved=stats_over[0]["resolved"],
        stale_resolves=stats_over[0]["stale_resolves"],
        overlap_saved_ms=round(saved_s * 1e3, 2),
    )
    _emit(
        "overlapped_sync_step_loop_ms",
        round(wall_over * 1e3, 2),
        "ms/loop",
        round(wall_block / wall_over, 3),
    )


def bench_config13() -> None:
    """Config 13: telemetry overhead — recorder off vs on over the config-11
    compiled-eager workload, plus a trace-export smoke.

    The ISSUE-8 acceptance measurement: the event journal must cost ~nothing
    on the compiled hot path. The config-11 workload (4-metric stat-score
    collection, compiled path pinned on, one donated-state XLA dispatch per
    step) runs interleaved off/on timing segments (interleaving cancels
    thermal/allocator drift; medians over REPS segments each). Asserts
    (CI gates contract):

    - recorder-ON overhead < 2 % of the recorder-off step time (+1 µs clock
      slack) — and the off state IS the shipped default, whose only cost is
      one ``journal.ACTIVE`` attribute read per dispatch (asserted
      allocation-free in tests/observability/test_disabled_overhead.py), so
      the recorder-off overhead is bounded by the same number;
    - the recorder actually recorded: one ``compiled.dispatch`` event per
      ON-segment step;
    - exporting the journal produces valid Chrome-trace JSON (parses, has
      the step-lane duration events).

    Emits `telemetry_recorder_on_step_us` with `vs_baseline` = on/off.
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    from metrics_tpu import F1, Precision, Recall, Specificity
    from metrics_tpu import observability as obs
    from metrics_tpu.core.collections import MetricCollection

    B, STEPS, SEGMENTS = 256, 30, 5
    rng = np.random.RandomState(13)
    preds = jnp.asarray(rng.rand(B, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (B,)))

    mc = MetricCollection(
        {
            "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
            "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
            "f1": F1(num_classes=NUM_CLASSES, average="macro"),
            "spec": Specificity(num_classes=NUM_CLASSES, average="macro"),
        },
    )
    for m in mc.values():
        m.compiled_update = True

    obs.disable()
    obs.clear()
    mc.update(preds, target)  # warm: group plan + trace
    jax.block_until_ready(mc["prec"]._state["tp"])

    def segment() -> float:
        t0 = time.perf_counter()
        for _ in range(STEPS):
            mc.update(preds, target)
        jax.block_until_ready(mc["prec"]._state["tp"])
        return (time.perf_counter() - t0) / STEPS * 1e6

    times = {"off": [], "on": []}
    for _ in range(SEGMENTS):
        obs.disable()
        times["off"].append(segment())
        obs.enable()
        times["on"].append(segment())
    obs.disable()
    off_us = float(np.median(times["off"]))
    on_us = float(np.median(times["on"]))
    overhead_us = on_us - off_us
    budget_us = 0.02 * off_us + 1.0
    assert overhead_us <= budget_us, (
        f"recorder-ON overhead {overhead_us:.2f} us/step exceeds the 2% "
        f"budget (+1 us clock slack = {budget_us:.2f} us on a "
        f"{off_us:.2f} us step)"
    )
    dispatch_events = obs.events(kinds=("compiled.dispatch",))
    assert len(dispatch_events) == SEGMENTS * STEPS, (
        f"expected {SEGMENTS * STEPS} dispatch events, "
        f"recorded {len(dispatch_events)}"
    )

    # ---- trace-export smoke: a valid Chrome-trace JSON file ----
    with tempfile.NamedTemporaryFile("r", suffix=".json", delete=False) as f:
        trace_path = f.name
    obs.export_chrome_trace(trace_path)
    with open(trace_path) as f:
        trace = json.load(f)
    os.unlink(trace_path)
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    spans = [t for t in trace["traceEvents"] if t.get("ph") == "X"]
    assert len(spans) == SEGMENTS * STEPS
    assert all("ts" in t and "dur" in t and "pid" in t for t in spans)
    obs.clear()

    _diag(
        config=13,
        members=4,
        batch=B,
        steps_per_segment=STEPS,
        segments=SEGMENTS,
        step_us={"off": round(off_us, 2), "on": round(on_us, 2)},
        recorder_overhead_us=round(overhead_us, 3),
        recorder_overhead_pct=round(100.0 * overhead_us / off_us, 2),
        events_recorded=len(dispatch_events),
        trace_export="valid chrome-trace JSON "
        f"({len(trace['traceEvents'])} events)",
    )
    _emit(
        "telemetry_recorder_on_step_us",
        round(on_us, 2),
        "us/step",
        round(on_us / off_us, 4),
    )


def bench_config14() -> None:
    """Config 14: fleet resilience — quorum-degraded sync over the FleetWorld
    fault simulator: readmission latency after a transient partition (swept
    over world size) and the capacity-retention curve as ranks die.

    The ISSUE-16 acceptance measurement: ``on_missing="quorum"`` must turn
    rank loss from a fleet-wide abort into a bounded, self-healing
    degradation. Two deterministic scenarios run over FleetWorld (threads
    harness with declarative FaultProfile fault injection, round-indexed so
    every run is bit-reproducible):

    **Recovery sweep** (W in 8/32): one rank is partitioned for two sync
    rounds (``drop_rounds``). Survivors must shrink to a quorum within the
    faulted round and the partitioned rank must be readmitted within ONE
    round of the partition healing — with zero manual
    ``reset_channel_health()`` calls (the probation state machine does the
    readmission). Asserts (CI gates contract):

    - pre-fault rounds never degrade (full membership, epoch 0);
    - readmission completes in exactly one post-heal round at every swept
      world size, ending at full membership;
    - the ``channel_resets`` gauge is unchanged (no manual resets) while
      ``quorum_shrinks``/``quorum_readmits`` advanced;
    - survivors' synced values are bit-equal to each other every round.

    **Degradation curve** (W=16, k in 0/2/4 ranks preempted at step 1):
    survivors converge in one membership epoch and keep syncing; the curve
    records the aggregate capacity retained (survivor sum / full-fleet sum
    at the final round) per dead-rank count. Asserts the k=0 run never
    degrades a round and matches the analytic full-fleet sum, and that for
    every k the survivors agree bit-for-bit on the final value.

    Emits `fleet_readmit_rounds` (rounds from partition heal to full
    readmission, max over the W sweep) with `vs_baseline` = the degraded
    fraction of gather rounds in the W=32 recovery run.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu.observability.registry import process_snapshot
    from metrics_tpu.parallel import resilience
    from metrics_tpu.parallel.bucketing import clear_sync_plan_cache
    from metrics_tpu.parallel.sync import host_sync_state
    from tests.helpers.fake_world import FaultProfile, FleetWorld

    class _Patch:
        """Minimal monkeypatch.setattr stand-in for FleetWorld.install."""

        def __init__(self):
            self._saved = []

        def setattr(self, obj, name, value):
            self._saved.append((obj, name, getattr(obj, name)))
            setattr(obj, name, value)

        def undo(self):
            while self._saved:
                obj, name, val = self._saved.pop()
                setattr(obj, name, val)

    def run_fleet(world_size, profile, steps):
        """Drive `steps` quorum sync rounds; returns per-rank tracks of
        (synced_sum, membership_epoch, live_ranks), the world (for its
        gather counters), and the wall-clock of the whole drive."""
        world = FleetWorld(world_size, profile)
        patch = _Patch()
        clear_sync_plan_cache()
        world.install(patch)
        try:

            def body(rank):
                track = []
                for step in range(steps):
                    world.begin_round(rank, step)
                    synced = host_sync_state(
                        {"s": jnp.asarray(float(rank + step))},
                        {"s": "sum"},
                        update_count=1,
                        timeout=0,
                        on_missing="quorum",
                        metric_name="bench14",
                    )
                    track.append(
                        (
                            float(np.asarray(synced["s"])),
                            resilience.membership_epoch(),
                            resilience.live_ranks(),
                        )
                    )
                return track

            t0 = time.perf_counter()
            results = world.run(body, timeout=300.0)
            wall = time.perf_counter() - t0
        finally:
            world.uninstall()
            patch.undo()
            clear_sync_plan_cache()
        return results, world, wall

    # ---- recovery sweep: transient 2-round partition, W in 8/32 ----
    DROP_RANK, DROP_START, DROP_N, STEPS = 3, 2, 2, 7
    heal_step = DROP_START + DROP_N
    before = process_snapshot()
    recovery = []
    for W in (8, 32):
        results, world, wall = run_fleet(
            W,
            FaultProfile(drop_rounds={DROP_RANK: (DROP_START, DROP_N)}),
            STEPS,
        )
        full = tuple(range(W))
        survivors = [r for r in range(W) if r != DROP_RANK]
        for rank in survivors:
            track = results[rank]
            for step in range(DROP_START):  # pre-fault: never degraded
                assert track[step][1:] == (0, full), (W, rank, step, track[step])
            # survivors agree bit-for-bit with each other every round
            assert track == results[survivors[0]], (W, rank)
        # readmission: first full-membership round at/after the heal
        sample = results[survivors[0]]
        t_full = next(
            t for t in range(heal_step, STEPS) if sample[t][2] == full
        )
        readmit_rounds = t_full - heal_step + 1
        assert readmit_rounds == 1, (W, [v[1:] for v in sample])
        assert sample[-1][0] == float(sum(r + (STEPS - 1) for r in range(W)))
        assert world.gather_rounds_degraded > 0, W
        recovery.append(
            {
                "world": W,
                "readmit_rounds": readmit_rounds,
                "degraded_gather_fraction": round(
                    world.gather_rounds_degraded / world.gather_rounds_total, 4
                ),
                "wall_ms": round(wall * 1e3, 2),
            }
        )
    after = process_snapshot()
    assert after["channel_resets"] == before["channel_resets"], (
        "readmission must not require manual reset_channel_health()"
    )
    assert after["quorum_shrinks"] > before["quorum_shrinks"]
    assert after["quorum_readmits"] > before["quorum_readmits"]

    # ---- degradation curve: k dead ranks at step 1, capacity retained ----
    W, STEPS_K = 16, 6
    curve = []
    full_sum = None
    for k in (0, 2, 4):
        dead = {W - 1 - i: 1 for i in range(k)}
        results, world, wall = run_fleet(
            W, FaultProfile(preempt_at=dead), STEPS_K
        )
        assert world.preempted == set(dead), (k, world.preempted)
        survivors = [r for r in range(W) if r not in dead]
        final = results[survivors[0]][-1]
        for rank in survivors:  # bit-equal survivor agreement
            assert results[rank][-1] == final, (k, rank)
        expect = float(sum(r + (STEPS_K - 1) for r in survivors))
        assert final[0] == expect, (k, final, expect)
        if k == 0:
            full_sum = final[0]
            assert world.gather_rounds_degraded == 0
            assert final[1:] == (0, tuple(range(W)))
        else:
            assert final[1] == 1, (k, final)  # ONE membership epoch
        curve.append(
            {
                "dead": k,
                "survivors": len(survivors),
                "epoch": final[1],
                "capacity_retained": round(final[0] / full_sum, 4),
                "wall_ms": round(wall * 1e3, 2),
            }
        )

    readmit_max = max(r["readmit_rounds"] for r in recovery)
    _diag(
        config=14,
        recovery_sweep=recovery,
        drop={"rank": DROP_RANK, "rounds": [DROP_START, DROP_START + DROP_N - 1]},
        degradation_curve=curve,
        steps={"recovery": STEPS, "degradation": STEPS_K},
    )
    _emit(
        "fleet_readmit_rounds",
        readmit_max,
        "rounds",
        recovery[-1]["degraded_gather_fraction"],
    )


def bench_config15() -> None:
    """Config 15: whole-step fused program — ``update + in-jit sync(fused) +
    compute`` as ONE cached XLA program (``core/plan.py``) vs the PR-5
    compiled update + separate blocking host sync, over the config-11
    stat-score workload at simulated W=8.

    The ISSUE-17 acceptance measurement. The fused side runs the 4-member
    Precision/Recall/F1/Specificity collection inside a user-style
    ``jax.jit(shard_map(step))`` over 8 devices (CPU runs force
    ``--xla_force_host_platform_device_count=8``; ``main()`` injects the
    flag before backend init when config 15 is requested): per step the
    sharded batch updates, the bucketed fused psum syncs, and every member
    computes — one donated dispatch, values served every step. The legacy
    side is the config-11 compiled stateful update per rank over the
    LockstepWorld W=8 threads harness plus the separate blocking host sync
    (``sync(); compute(); unsync()``) each step — the pre-plan way to get
    the same per-step synced values. Asserts (CI gates contract):

    - exactly ONE XLA program serves the whole fused step: the jitted
      step's executable cache holds 1 entry after the loop (no retrace
      churn) and the plan binding holds exactly 1 cached inline program;
    - the fused values are **bit-identical** to the legacy host-synced
      values at every compared step (integer stat-score states make this
      exact, not approximate);
    - fused step time ≤ the update-ONLY sharded program × 1.5 at the SAME
      W=8 (the config-11 path's work, re-measured in-process over the same
      mesh): the in-program sync + all 4 computes must ride along for a
      bounded fraction of the step, not double it (on real TPU ICI the
      collective overlaps with compute; forced CPU devices pay memcpy
      collectives, hence the margin — the W=1 config-11 number rides the
      diagnostic line for reference);
    - fused step time strictly below the legacy compiled-update +
      host-sync-per-step loop.

    Emits ``fused_whole_step_us`` with ``vs_baseline`` = legacy/fused.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    import metrics_tpu.parallel.sync as sync_mod
    from metrics_tpu import F1, Precision, Recall, Specificity
    from metrics_tpu.core import plan as plan_mod
    from metrics_tpu.core.collections import MetricCollection
    from metrics_tpu.parallel.bucketing import clear_sync_plan_cache
    from tests.helpers.fake_world import LockstepWorld

    W, B, STEPS, EQ_STEPS = 8, 256, 30, 8
    devs = jax.devices()
    if len(devs) < W:
        raise RuntimeError(
            f"config 15 needs {W} devices for the in-jit fused sync; got "
            f"{len(devs)} (CPU runs need XLA_FLAGS="
            f"--xla_force_host_platform_device_count={W}, injected by main() "
            "when --config includes 15)"
        )
    rng = np.random.RandomState(15)
    preds = [jnp.asarray(rng.rand(B, NUM_CLASSES).astype(np.float32)) for _ in range(EQ_STEPS)]
    target = [jnp.asarray(rng.randint(0, NUM_CLASSES, (B,))) for _ in range(EQ_STEPS)]

    def make_stats() -> MetricCollection:
        return MetricCollection(
            {
                "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
                "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
                "f1": F1(num_classes=NUM_CLASSES, average="macro"),
                "spec": Specificity(num_classes=NUM_CLASSES, average="macro"),
            }
        )

    def shard(x):
        return x.reshape((W, B // W) + x.shape[1:])

    # ---- fused whole-step: ONE donated program inside the user's jit ----
    plan_mod.clear_plans()
    mesh = Mesh(np.array(devs[:W]), ("w",))
    col = make_stats()

    @partial(jax.jit, donate_argnums=(0,))
    @partial(shard_map, mesh=mesh, in_specs=(P("w"), P("w"), P("w")), out_specs=(P("w"), P()))
    def fused_step(state, p, t):
        st = jax.tree_util.tree_map(lambda x: x[0], state)
        ns, vals = col.compiled_step(st, p[0], t[0], axis_name="w")
        return jax.tree_util.tree_map(lambda x: x[None], ns), vals

    carry_sharding = jax.sharding.NamedSharding(mesh, P("w"))

    def fresh_carry():
        # pin the initial carry to the same sharding the step outputs, or the
        # second call would see a different input layout and retrace
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.stack([x] * W), carry_sharding),
            col.init_state(),
        )

    state = fresh_carry()
    fused_values = []
    for i in range(EQ_STEPS):
        state, vals = fused_step(state, shard(preds[i]), shard(target[i]))
        fused_values.append({k: np.asarray(v).copy() for k, v in vals.items()})

    state = fresh_carry()
    state, _ = fused_step(state, shard(preds[0]), shard(target[0]))  # warm
    jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, vals = fused_step(state, shard(preds[0]), shard(target[0]))
    jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    fused_us = (time.perf_counter() - t0) / STEPS * 1e6

    cache_size = getattr(fused_step, "_cache_size", lambda: 1)()
    assert cache_size == 1, f"fused step retraced: executable cache {cache_size} != 1"
    inline_programs = len(plan_mod.peek_binding(col).programs)
    assert inline_programs == 1, f"plan binding holds {inline_programs} programs != 1"

    # ---- update-ONLY sharded program: the same work minus sync+compute ----
    @partial(jax.jit, donate_argnums=(0,))
    @partial(shard_map, mesh=mesh, in_specs=(P("w"), P("w"), P("w")), out_specs=P("w"))
    def update_only_step(state, p, t):
        st = jax.tree_util.tree_map(lambda x: x[0], state)
        ns = col.pure_update(st, p[0], t[0])
        return jax.tree_util.tree_map(lambda x: x[None], ns)

    state = fresh_carry()
    state = update_only_step(state, shard(preds[0]), shard(target[0]))  # warm
    jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state = update_only_step(state, shard(preds[0]), shard(target[0]))
    jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    update_sharded_us = (time.perf_counter() - t0) / STEPS * 1e6

    # ---- legacy: PR-5 compiled update + separate blocking host sync ----
    def run_legacy():
        world = LockstepWorld(W)
        saved = (jax.process_count, sync_mod._raw_process_allgather)
        clear_sync_plan_cache()
        values = [[] for _ in range(W)]
        try:
            jax.process_count = lambda: W
            sync_mod._raw_process_allgather = world.allgather

            def body(rank):
                mc = make_stats()
                for m in mc.values():
                    m.compiled_update = True  # engage immediately (skip warm-up)
                    m.sync_timeout = 0  # inline watchdog: thread-local survives
                    m.distributed_available_fn = lambda: True
                for i in range(EQ_STEPS):
                    mc.update(shard(preds[i])[rank], shard(target[i])[rank])
                    mc.sync(timeout=0)
                    values[rank].append(
                        {k: np.asarray(v).copy() for k, v in mc.compute().items()}
                    )
                    mc.unsync()
                # timed window: same steady-state step, batch 0 repeated
                t0 = time.perf_counter()
                for _ in range(STEPS):
                    mc.update(shard(preds[0])[rank], shard(target[0])[rank])
                    mc.sync(timeout=0)
                    mc.compute()
                    mc.unsync()
                return time.perf_counter() - t0

            elapsed = world.run(body, timeout=600.0)
        finally:
            jax.process_count, sync_mod._raw_process_allgather = saved
            world.shutdown_executors()
            clear_sync_plan_cache()
        return max(elapsed) / STEPS * 1e6, values

    legacy_us, legacy_values = run_legacy()

    # ---- bit-identity: fused values == legacy host-synced values ----
    for i in range(EQ_STEPS):
        ref = legacy_values[0][i]
        assert sorted(fused_values[i]) == sorted(ref)
        for k in ref:
            a, b = fused_values[i][k], ref[k]
            assert a.dtype == b.dtype and a.tobytes() == b.tobytes(), (
                f"step {i} value {k} diverged fused vs legacy host sync"
            )

    # ---- step-time gates ----
    mc = make_stats()
    for m in mc.values():
        m.compiled_update = True
    mc.update(preds[0], target[0])  # warm: group plan + trace
    jax.block_until_ready(mc["prec"]._state["tp"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        mc.update(preds[0], target[0])
    jax.block_until_ready(mc["prec"]._state["tp"])
    update_w1_us = (time.perf_counter() - t0) / STEPS * 1e6  # config-11 reference

    assert fused_us <= update_sharded_us * 1.5, (
        f"fused whole step {fused_us:.1f}us/step exceeds the update-only "
        f"sharded program {update_sharded_us:.1f}us/step x1.5 — the in-program "
        "sync+compute increment is out of bounds"
    )
    assert fused_us < legacy_us, (
        f"fused whole step {fused_us:.1f}us/step not below legacy compiled "
        f"update + host sync {legacy_us:.1f}us/step"
    )

    _diag(
        config=15,
        world=W,
        batch=B,
        fused_step_us=round(fused_us, 2),
        update_only_sharded_us=round(update_sharded_us, 2),
        compiled_update_w1_us=round(update_w1_us, 2),
        legacy_update_plus_host_sync_us=round(legacy_us, 2),
        dispatches_per_step=1,
        executable_cache=cache_size,
        equality=f"bit-identical over {EQ_STEPS} synced steps (W={W})",
    )
    _emit(
        "fused_whole_step_us",
        round(fused_us, 2),
        "us/step",
        round(legacy_us / fused_us, 3),
    )


def bench_config16() -> None:
    """Config 16: topology-aware hierarchical sync — tiered two-level
    schedule vs the flat world gather at simulated W=16, tier_size=4.

    The ISSUE-20 acceptance measurement: a mixed reduce+cat state dict
    host-syncs for several rounds over a FleetWorld whose latency model
    charges ``(k-1)`` ring hops per collective — inter-tier hops when the
    participant set spans tiers, intra-tier hops otherwise — once with no
    tier map (the flat path: every payload collective is a full 16-rank
    gather on the slow wire) and once with ``set_tier_map(4)`` (the tiered
    path: reduce-within-tier, ONE leaders-only inter-tier exchange per
    bucket, intra-tier broadcast). Asserts (CI gates contract):

    - tiered values are **bit-identical** to the flat gather's on every
      rank (full precision moves raw blocks; same floats, fewer slow hops);
    - the inter-tier exchange runs over n_tiers=4 participants, strictly
      fewer than the flat gather's 16;
    - the tiered schedule's inter-tier bytes (per-hop telemetry counters)
      are STRICTLY below what the flat gather moves across tiers for the
      same payloads (``inter_tier_bytes + inter_tier_bytes_saved`` — the
      counters' own definition of the flat cost);
    - tiered wall-clock beats flat under the fleet's tiered latency model
      (4-participant slow hops + cheap fast hops < 16-participant slow
      hops).

    Emits ``tiered_sync_inter_tier_bytes`` with ``vs_baseline`` =
    flat/tiered inter-tier byte ratio (>1 is a win).
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    import metrics_tpu.parallel.async_sync as async_mod
    import metrics_tpu.parallel.sync as sync_mod
    from metrics_tpu.core.plan import clear_plans
    from metrics_tpu.parallel import tiering
    from metrics_tpu.parallel.bucketing import clear_sync_plan_cache
    from tests.helpers.fake_world import FaultProfile, FleetWorld

    W, TIER, ROUNDS = 16, 4, 2
    # hop latencies are large enough that the simulated wire dominates the
    # 16-thread harness's Python overhead: flat pays (W-1)=15 slow hops per
    # payload collective, tiered pays (n_tiers-1)=3 slow hops (leaders only)
    # plus (TIER-1)=3 fast hops on each side of the exchange
    INTER_HOP_S = 0.02  # slow-wire (DCN) per ring hop
    INTRA_HOP_S = INTER_HOP_S / 20  # fast in-tier wire

    def run_mode(tiered: bool):
        world = FleetWorld(
            W,
            FaultProfile(
                tier_size=TIER,
                intra_tier_latency_s=INTRA_HOP_S,
                inter_tier_latency_s=INTER_HOP_S,
            ),
        )
        saved = (
            jax.process_count,
            sync_mod._raw_process_allgather,
            async_mod._get_executor,
            async_mod._current_domain,
            tiering._current_rank,
        )
        clear_sync_plan_cache()
        clear_plans()
        tiering.reset_tiering()
        try:
            jax.process_count = lambda: W
            sync_mod._raw_process_allgather = world.allgather
            async_mod._get_executor = world.executor_for_current_rank
            async_mod._current_domain = world.rank_domain
            tiering._current_rank = lambda: world.rank_domain() or 0
            if tiered:
                tiering.set_tier_map(TIER)
                tiering.set_tier_transport(world)

            def body(rank):
                stats = {}
                vals = []
                t0 = _time.perf_counter()
                for step in range(ROUNDS):
                    state = {
                        "acc": jnp.arange(512, dtype=jnp.float32) * (rank + 1) + step,
                        "cnt": jnp.asarray(rank + step + 1, jnp.int32),
                        "rows": [jnp.arange(4 + rank % 3, dtype=jnp.float32) + rank],
                    }
                    synced = sync_mod.host_sync_state(
                        state, {"acc": "sum", "cnt": "sum", "rows": "cat"},
                        update_count=1, timeout=0, metric_name="tiered-bench",
                        stats=stats,
                    )
                    vals.append(
                        (
                            np.asarray(synced["acc"]).tobytes(),
                            np.asarray(synced["cnt"]).tobytes(),
                            tuple(np.asarray(r).tobytes() for r in synced["rows"]),
                        )
                    )
                elapsed = _time.perf_counter() - t0
                topo = tiering.active_topology()
                return vals, stats, elapsed, None if topo is None else topo.n_tiers
            results = world.run(body, timeout=300.0)
        finally:
            (
                jax.process_count,
                sync_mod._raw_process_allgather,
                async_mod._get_executor,
                async_mod._current_domain,
                tiering._current_rank,
            ) = saved
            tiering.reset_tiering()
            clear_plans()
            clear_sync_plan_cache()
            world.shutdown_executors()
        return results

    flat = run_mode(tiered=False)
    tiered = run_mode(tiered=True)

    # bit-identity: full-precision tiered == flat, every rank, every round
    for rank in range(W):
        assert tiered[rank][0] == flat[rank][0], f"rank {rank} diverged"

    # participants: the slow hop carries the 4 tier leaders, not 16 ranks
    inter_participants = tiered[0][3]
    assert inter_participants == W // TIER, inter_participants
    assert inter_participants < W

    # bytes: strictly fewer inter-tier bytes than the flat gather moves
    # across tiers (the saved counter IS flat-minus-actual by definition)
    tiered_inter = sum(t[1].get("inter_tier_bytes", 0) for t in tiered)
    saved_bytes = sum(t[1].get("inter_tier_bytes_saved", 0) for t in tiered)
    flat_inter = tiered_inter + saved_bytes
    assert tiered_inter > 0 and saved_bytes > 0
    assert tiered_inter < flat_inter, (tiered_inter, flat_inter)

    # wall-clock: leaders-only slow hops beat 16-participant slow hops
    wall_flat = max(r[2] for r in flat)
    wall_tiered = max(r[2] for r in tiered)
    assert wall_tiered < wall_flat, (
        f"tiered step loop {wall_tiered * 1e3:.1f} ms not below flat "
        f"{wall_flat * 1e3:.1f} ms under the tiered latency model"
    )

    _diag(
        config=16,
        world=W,
        tier_size=TIER,
        rounds=ROUNDS,
        inter_participants={"flat": W, "tiered": inter_participants},
        inter_tier_bytes={"flat": flat_inter, "tiered": tiered_inter},
        intra_tier_bytes=sum(t[1].get("intra_tier_bytes", 0) for t in tiered),
        wall_ms={"flat": round(wall_flat * 1e3, 2), "tiered": round(wall_tiered * 1e3, 2)},
        latency_model={
            "inter_hop_ms": INTER_HOP_S * 1e3,
            "intra_hop_ms": INTRA_HOP_S * 1e3,
            "ring": "(participants-1) hops per collective",
        },
        equality="bit-identical (full precision, reduce + cat)",
    )
    _emit(
        "tiered_sync_inter_tier_bytes",
        tiered_inter,
        "bytes",
        round(flat_inter / tiered_inter, 3),
    )


def main() -> None:
    if "--config" in sys.argv:
        # config 15's in-jit fused sync needs 8 devices; on CPU hosts that
        # means forcing virtual devices BEFORE the backend initializes
        i = sys.argv.index("--config") + 1
        raw = sys.argv[i] if i < len(sys.argv) else ""
        if "15" in [k.strip() for k in raw.split(",")]:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
    try:
        platform = _ensure_backend()
        _enable_persistent_compile_cache()
        _diag(platform=platform)
        ours = bench_ours()
    except Exception as e:  # noqa: BLE001 — contract line must appear no matter what
        print(
            json.dumps(
                {
                    "metric": "fused_metric_step_time",
                    "value": None,
                    "unit": "us/step",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {e}"[:500],
                }
            )
        )
        raise SystemExit(0)
    try:
        base = bench_torch_baseline()
        vs = base / ours
    except Exception:
        vs = None
    _emit("fused_metric_step_time", round(ours * 1e6, 2), "us/step", round(vs, 3) if vs else None)
    extra = {"2": bench_config2, "3": bench_config3, "4": bench_config4, "5": bench_config5, "6": bench_config6, "7": bench_config7, "8": bench_config8, "9": bench_config9, "10": bench_config10, "11": bench_config11, "12": bench_config12, "13": bench_config13, "14": bench_config14, "15": bench_config15, "16": bench_config16}
    if "--config" in sys.argv:
        # comma-separated list (--config 9,11): related configs run in one
        # process and share compile-cache warmth (CI gates contract)
        i = sys.argv.index("--config") + 1
        raw = sys.argv[i] if i < len(sys.argv) else None
        keys = [k.strip() for k in raw.split(",") if k.strip()] if raw else []
        bad = [k for k in keys if k not in extra]
        if bad or not keys:
            print(json.dumps({"diagnostic": f"--config takes a comma-separated list from {sorted(extra)} (config 1 always runs); got {raw!r}"}), file=sys.stderr)
        wanted = [extra[k] for k in keys if k in extra]
    elif "--all" in sys.argv:
        wanted = list(extra.values())
    else:
        wanted = []
    for cfg in wanted:
        try:
            cfg()
        except Exception as e:  # noqa: BLE001 — keep later configs running
            print(json.dumps({"diagnostic": f"{cfg.__name__} failed", "error": str(e)[:500]}), file=sys.stderr)


if __name__ == "__main__":
    main()
