"""Text metric tests: golden values, independent hand-rolled references, and
distributed merge semantics (mirrors the reference's `tests/text/` strategy,
which compares against jiwer/nltk/rouge-score — absent here, so references
are independently implemented in-test)."""
from collections import Counter

import numpy as np
import pytest

from metrics_tpu import BLEUScore, ROUGEScore, WER
from metrics_tpu.functional import bleu_score, embedding_similarity, rouge_score, wer
from metrics_tpu.functional.text.rouge import PorterStemmer
from metrics_tpu.functional.text.wer import _edit_distance


# ---------------------------------------------------------------------------
# WER
# ---------------------------------------------------------------------------


def _py_edit_distance(a, b):
    """Plain-python Levenshtein (independent of the vectorized one)."""
    dp = [[0] * (len(b) + 1) for _ in range(len(a) + 1)]
    for i in range(len(a) + 1):
        dp[i][0] = i
    for j in range(len(b) + 1):
        dp[0][j] = j
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            if a[i - 1] == b[j - 1]:
                dp[i][j] = dp[i - 1][j - 1]
            else:
                dp[i][j] = min(dp[i - 1][j], dp[i][j - 1], dp[i - 1][j - 1]) + 1
    return dp[-1][-1]


PREDS = ["this is the prediction", "there is an other sample"]
REFS = ["this is the reference", "there is another one"]


def test_wer_golden():
    assert float(wer(PREDS, REFS)) == pytest.approx(0.5)
    assert float(wer("hello world", "hello world")) == 0.0


@pytest.mark.parametrize("seed", range(5))
def test_edit_distance_vs_python(seed):
    rng = np.random.RandomState(seed)
    vocab = ["a", "b", "c", "d", "e"]
    a = [vocab[i] for i in rng.randint(0, 5, rng.randint(0, 20))]
    b = [vocab[i] for i in rng.randint(0, 5, rng.randint(0, 20))]
    assert _edit_distance(a, b) == _py_edit_distance(a, b)


def test_wer_class_accumulation_and_merge():
    m = WER()
    m.update(PREDS[:1], REFS[:1])
    m.update(PREDS[1:], REFS[1:])
    assert float(m.compute()) == pytest.approx(float(wer(PREDS, REFS)))

    # distributed merge: two "ranks" then merge_states == all data
    m1, m2 = WER(), WER()
    m1.update(PREDS[:1], REFS[:1])
    m2.update(PREDS[1:], REFS[1:])
    merged = m1.merge_states(m1._state, m2._state)
    assert float(m1.pure_compute(merged)) == pytest.approx(float(wer(PREDS, REFS)))


# ---------------------------------------------------------------------------
# BLEU
# ---------------------------------------------------------------------------

TRANS = ["the cat is on the mat".split(), "a dog walks in the park".split()]
REFS_BLEU = [
    ["there is a cat on the mat".split(), "a cat is on the mat".split()],
    ["the dog walks in a park".split()],
]


def _py_bleu(refs, trans, n_gram=4, smooth=False):
    """Independent BLEU: clipped modified precision + brevity penalty."""

    def counts(tokens, n):
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    num = np.zeros(n_gram)
    den = np.zeros(n_gram)
    t_len = r_len = 0
    for t, rs in zip(trans, refs):
        t_len += len(t)
        diffs = [abs(len(t) - len(r)) for r in rs]
        r_len += len(rs[int(np.argmin(diffs))])
        for n in range(1, n_gram + 1):
            tc = counts(t, n)
            best = Counter()
            for r in rs:
                rc = counts(r, n)
                for g in rc:
                    best[g] = max(best[g], rc[g])
            for g, c in tc.items():
                num[n - 1] += min(c, best[g])
                den[n - 1] += c
    if num.min() == 0 and not smooth:
        return 0.0
    if smooth:
        prec = (num + 1) / (den + 1)
        prec[0] = num[0] / den[0]
    else:
        prec = num / den
    gm = np.exp(np.mean(np.log(prec)))
    bp = 1.0 if t_len > r_len else np.exp(1 - r_len / t_len)
    return float(bp * gm)


def test_bleu_golden():
    tc = ["the cat is on the mat".split()]
    rc = [["there is a cat on the mat".split(), "a cat is on the mat".split()]]
    assert float(bleu_score(rc, tc)) == pytest.approx(0.7598, abs=1e-4)


@pytest.mark.parametrize("n_gram", [1, 2, 3, 4])
@pytest.mark.parametrize("smooth", [False, True])
def test_bleu_vs_python(n_gram, smooth):
    ours = float(bleu_score(REFS_BLEU, TRANS, n_gram=n_gram, smooth=smooth))
    theirs = _py_bleu(REFS_BLEU, TRANS, n_gram=n_gram, smooth=smooth)
    assert ours == pytest.approx(theirs, abs=1e-5)


def test_bleu_class_matches_corpus():
    m = BLEUScore()
    for t, r in zip(TRANS, REFS_BLEU):
        m.update([r], [t])
    assert float(m.compute()) == pytest.approx(float(bleu_score(REFS_BLEU, TRANS)), abs=1e-6)


def test_bleu_size_mismatch():
    with pytest.raises(ValueError, match="Corpus has different size"):
        bleu_score([["a b".split()]], [])


# ---------------------------------------------------------------------------
# ROUGE
# ---------------------------------------------------------------------------


def _py_rouge1_f(pred, target):
    p = Counter(pred.lower().split())
    t = Counter(target.lower().split())
    hits = sum((p & t).values())
    if hits == 0:
        return 0.0
    prec, rec = hits / sum(p.values()), hits / sum(t.values())
    return 2 * prec * rec / (prec + rec)


def test_rouge_golden():
    scores = rouge_score("My name is John", "Is your name John")
    assert float(scores["rouge1_fmeasure"]) == pytest.approx(0.75)
    assert float(scores["rouge2_fmeasure"]) == pytest.approx(0.0)
    assert float(scores["rougeL_fmeasure"]) == pytest.approx(0.5)


@pytest.mark.parametrize(
    "pred, target",
    [
        ("the quick brown fox", "the quick brown fox"),
        ("a b c d", "e f g h"),
        ("one two three four five", "one three five"),
    ],
)
def test_rouge1_vs_python(pred, target):
    scores = rouge_score(pred, target, rouge_keys="rouge1")
    assert float(scores["rouge1_fmeasure"]) == pytest.approx(_py_rouge1_f(pred, target), abs=1e-6)


def test_rouge_lcs_identity_and_disjoint():
    same = rouge_score("alpha beta gamma", "alpha beta gamma", rouge_keys="rougeL")
    assert float(same["rougeL_fmeasure"]) == pytest.approx(1.0)
    disjoint = rouge_score("alpha beta", "gamma delta", rouge_keys="rougeL")
    assert float(disjoint["rougeL_fmeasure"]) == 0.0


def test_rouge_unknown_key():
    with pytest.raises(ValueError, match="unknown rouge key"):
        rouge_score("a", "a", rouge_keys="rouge42")
    with pytest.raises(ValueError, match="unknown rouge key"):
        ROUGEScore(rouge_keys="rouge42")


def test_rouge_class_accumulation():
    preds = ["My name is John", "The sky is blue today"]
    targets = ["Is your name John", "The sky was blue yesterday"]
    m = ROUGEScore(rouge_keys=("rouge1", "rougeL"))
    for p, t in zip(preds, targets):
        m.update([p], [t])
    batched = rouge_score(preds, targets, rouge_keys=("rouge1", "rougeL"))
    streamed = m.compute()
    for key in batched:
        assert float(streamed[key]) == pytest.approx(float(batched[key]), abs=1e-6)


@pytest.mark.parametrize(
    "word, stem",
    [
        ("caresses", "caress"),
        ("ponies", "poni"),
        ("cats", "cat"),
        ("agreed", "agre"),
        ("plastered", "plaster"),
        ("motoring", "motor"),
        ("conflated", "conflat"),
        ("hopping", "hop"),
        ("happy", "happi"),
        ("relational", "relat"),
        ("generalizations", "gener"),
        ("oscillators", "oscil"),
    ],
)
def test_porter_stemmer_golden(word, stem):
    assert PorterStemmer().stem(word) == stem


# ---------------------------------------------------------------------------
# embedding_similarity
# ---------------------------------------------------------------------------


def test_embedding_similarity():
    rng = np.random.RandomState(0)
    batch = rng.randn(6, 8).astype(np.float32)
    normed = batch / np.linalg.norm(batch, axis=1, keepdims=True)
    expected = normed @ normed.T
    np.fill_diagonal(expected, 0.0)
    got = np.asarray(embedding_similarity(batch))
    np.testing.assert_allclose(got, expected, atol=1e-5)

    got_dot = np.asarray(embedding_similarity(batch, similarity="dot", zero_diagonal=False))
    np.testing.assert_allclose(got_dot, batch @ batch.T, atol=1e-4)

    got_mean = np.asarray(embedding_similarity(batch, reduction="mean"))
    np.testing.assert_allclose(got_mean, expected.mean(-1), atol=1e-5)


def test_rouge_lsum_union_lcs_differs_from_rougel():
    # sentence order flipped: whole-text LCS (rougeL) penalizes order,
    # summary-level union-LCS (rougeLsum) must score it perfectly
    pred = "The cat sat. The dog barked."
    target = "The dog barked. The cat sat."
    scores = rouge_score(pred, target, rouge_keys=("rougeL", "rougeLsum"))
    assert float(scores["rougeLsum_fmeasure"]) == pytest.approx(1.0)
    assert float(scores["rougeL_fmeasure"]) < 1.0


def test_wer_length_mismatch():
    with pytest.raises(ValueError, match="must be the same"):
        wer(["a b", "c d"], ["a b"])
