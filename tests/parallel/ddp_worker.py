"""Worker for the real 2-process DDP sync test (run via subprocess).

The analogue of the reference's per-rank ``_class_test`` body
(``tests/helpers/testers.py:104-207``): rank-strided batches, per-rank
``update``, then ``compute()`` must equal the single-process reference over
ALL ranks' data. Run as:

    python ddp_worker.py <rank> <world> <port>
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

RANK, WORLD, PORT = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(
    coordinator_address=f"localhost:{PORT}", num_processes=WORLD, process_id=RANK
)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from sklearn.metrics import accuracy_score, roc_auc_score  # noqa: E402

from metrics_tpu import AUROC, Accuracy, MeanSquaredError, PearsonCorrcoef  # noqa: E402

NUM_BATCHES, BATCH, C = 6, 32, 5
rng = np.random.RandomState(42)
probs = rng.rand(NUM_BATCHES, BATCH, C).astype(np.float32)
labels = rng.randint(0, C, (NUM_BATCHES, BATCH))
bin_probs = rng.rand(NUM_BATCHES, BATCH).astype(np.float32)
bin_labels = rng.randint(0, 2, (NUM_BATCHES, BATCH))
x = rng.randn(NUM_BATCHES, BATCH).astype(np.float32)
y = (0.5 * x + 0.1 * rng.randn(NUM_BATCHES, BATCH)).astype(np.float32)


def _assert_close(ours, want, atol, what):
    ours = float(np.asarray(ours))
    assert abs(ours - want) <= atol, f"rank{RANK} {what}: {ours} != {want}"


# -- sum-state metric: Accuracy -------------------------------------------
acc = Accuracy(num_classes=C)
for i in range(RANK, NUM_BATCHES, WORLD):
    acc.update(jnp.asarray(probs[i]), jnp.asarray(labels[i]))
want = accuracy_score(labels.reshape(-1), probs.argmax(-1).reshape(-1))
_assert_close(acc.compute(), want, 1e-6, "accuracy")

# -- cat-state metric with UNEVEN per-rank rows: AUROC ---------------------
def _rows_for(rank: int, batch_idx: int) -> int:
    """Single source of truth for the ragged schedule: rank 0 contributes
    short batches. Drives BOTH the updates and the expected-value mask so
    they cannot drift (the batch→rank assignment is i % WORLD == rank)."""
    return BATCH if rank else BATCH - 7


auroc = AUROC()
for i in range(RANK, NUM_BATCHES, WORLD):
    n = _rows_for(RANK, i)
    auroc.update(jnp.asarray(bin_probs[i, :n]), jnp.asarray(bin_labels[i, :n]))
mask = np.zeros((NUM_BATCHES, BATCH), bool)
for r in range(WORLD):
    for i in range(r, NUM_BATCHES, WORLD):
        mask[i, : _rows_for(r, i)] = True
want = roc_auc_score(bin_labels[mask], bin_probs[mask])
_assert_close(auroc.compute(), want, 1e-6, "auroc-uneven")

# -- running-moment metric with pairwise merge: Pearson --------------------
pearson = PearsonCorrcoef()
for i in range(RANK, NUM_BATCHES, WORLD):
    pearson.update(jnp.asarray(x[i]), jnp.asarray(y[i]))
want = float(np.corrcoef(x.reshape(-1), y.reshape(-1))[0, 1])
_assert_close(pearson.compute(), want, 1e-4, "pearson")

# -- consistent-checkpoint pattern: sync_context + state_dict --------------
mse = MeanSquaredError()
mse.persistent(True)
for i in range(RANK, NUM_BATCHES, WORLD):
    mse.update(jnp.asarray(x[i]), jnp.asarray(y[i]))
with mse.sync_context():
    snap = mse.state_dict()
want_sse = float(((x - y) ** 2).sum())
assert abs(float(snap["sum_squared_error"]) - want_sse) < 1e-2, (
    f"rank{RANK} ckpt: {snap['sum_squared_error']} != {want_sse}"
)
# after the context, local (unsynced) state is restored
local = float(np.asarray(mse.sum_squared_error))
assert local < want_sse, f"rank{RANK} unsync restore failed"

print(f"rank{RANK} OK", flush=True)
