"""Precision / Recall module metrics.

Behavioral analogue of the reference's
``torchmetrics/classification/precision_recall.py`` (326 LoC): both subclass
:class:`StatScores` and reduce at compute time.
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.precision_recall import (
    _precision_compute,
    _recall_compute,
)


class _PrecisionRecallBase(StatScores):
    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        if average in ["macro", "weighted", "none", None] and (not num_classes or num_classes < 1):
            raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average


class Precision(_PrecisionRecallBase):
    r"""Precision :math:`\frac{TP}{TP + FP}` (reference ``precision_recall.py:28``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Precision
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> precision = Precision(num_classes=4, average="macro")
        >>> print(round(float(precision(preds, target)), 4))
        0.5
    """

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _precision_compute(tp, fp, fn, self.average, self.mdmc_reduce)


class Recall(_PrecisionRecallBase):
    r"""Recall :math:`\frac{TP}{TP + FN}` (reference ``precision_recall.py:180``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Recall
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> recall = Recall(num_classes=4, average="macro")
        >>> print(round(float(recall(preds, target)), 4))
        0.5
    """

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _recall_compute(tp, fp, fn, self.average, self.mdmc_reduce)
