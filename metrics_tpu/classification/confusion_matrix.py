"""ConfusionMatrix module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/confusion_matrix.py`` (145 LoC): one [C, C]
(or [C, 2, 2] multilabel) sum state, psum across the mesh.
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.confusion_matrix import (
    _confusion_matrix_compute,
    _confusion_matrix_update,
)


class ConfusionMatrix(Metric):
    """Confusion matrix with optional 'true'/'pred'/'all' normalization.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ConfusionMatrix
        >>> preds = jnp.asarray([1, 0, 1, 1])
        >>> target = jnp.asarray([1, 0, 0, 1])
        >>> confmat = ConfusionMatrix(num_classes=2)
        >>> print(confmat(preds, target).tolist())
        [[1, 1], [0, 2]]
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: int,
        normalize: Optional[str] = None,
        threshold: float = 0.5,
        multilabel: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.normalize = normalize
        self.threshold = threshold
        self.multilabel = multilabel

        allowed_normalize = ("true", "pred", "all", "none", None)
        if normalize not in allowed_normalize:
            raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")

        default = (
            jnp.zeros((num_classes, 2, 2), dtype=jnp.int32)
            if multilabel
            else jnp.zeros((num_classes, num_classes), dtype=jnp.int32)
        )
        self.add_state("confmat", default=default, dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        confmat = _confusion_matrix_update(
            preds, target, self.num_classes, self.threshold, self.multilabel
        )
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _confusion_matrix_compute(self.confmat, self.normalize)
