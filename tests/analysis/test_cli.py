"""CLI contract: exit codes, output format, --list-rules, error handling.

The CI gate runs the same commands over the package (exit 0) and the
violation fixtures (exit nonzero); these tests pin that contract in-process
(plus one true subprocess run for the ``python -m`` entry itself).
"""
import os
import subprocess
import sys

import pytest

from metrics_tpu.analysis.__main__ import main
from metrics_tpu.analysis.report import RULES

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
VIOLATING = sorted(
    os.path.join(FIXTURES, n) for n in os.listdir(FIXTURES) if n.startswith("violating_")
)


def test_violating_fixtures_exit_nonzero(capsys):
    assert VIOLATING, "violation fixtures missing"
    for path in VIOLATING:
        assert main([path]) == 1, f"{path} must fail the lint"
        out = capsys.readouterr().out
        assert os.path.basename(path) in out  # findings carry the path


def test_clean_and_suppressed_fixtures_exit_zero(capsys):
    assert main([os.path.join(FIXTURES, "clean_metric.py")]) == 0
    assert main([os.path.join(FIXTURES, "suppressed_metric.py")]) == 0


def test_finding_format_is_path_line_col_rule(capsys):
    main([os.path.join(FIXTURES, "violating_undeclared_state.py")])
    first = capsys.readouterr().out.splitlines()[0]
    path, line, col, rule = first.split(":", 3)
    assert path.endswith("violating_undeclared_state.py")
    assert int(line) > 0 and int(col) >= 0
    assert rule.strip().startswith("undeclared-state")


def test_list_rules_prints_catalog(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


def test_missing_path_is_usage_error(capsys):
    assert main([os.path.join(FIXTURES, "no_such_file.py")]) == 2


def test_unparsable_file_exits_nonzero(tmp_path, capsys):
    bad = tmp_path / "broken.py"
    bad.write_text("def update(:\n")
    assert main([str(bad)]) == 1
    assert "SyntaxError" in capsys.readouterr().err


def test_no_schedule_flag_skips_schedule_rules(capsys):
    path = os.path.join(FIXTURES, "violating_schedule.py")
    assert main([path]) == 1
    capsys.readouterr()
    assert main([path, "--no-schedule"]) == 0


def test_package_gate_via_module_subprocess():
    """The exact CI command: ``python -m metrics_tpu.analysis metrics_tpu/``
    exits 0 on the shipped package and 1 on a violation fixture."""
    import metrics_tpu

    pkg = os.path.dirname(metrics_tpu.__file__)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", pkg],
        capture_output=True, text=True, env=env,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", VIOLATING[0]],
        capture_output=True, text=True, env=env,
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
