"""SSIM parity vs an independent numpy/scipy oracle.

The reference validates SSIM against skimage (not shipped in this image), so
the oracle here is a from-scratch numpy implementation of Wang et al.'s
gaussian-weighted SSIM: separable gaussian window, local moments by VALID
2-D convolution (mathematically identical to the library's reflect-pad +
crop scheme on interior pixels), the standard (c1, c2) stabilized formula.
"""
import numpy as np
import pytest
from scipy.signal import convolve2d

import jax.numpy as jnp

from metrics_tpu import SSIM
from metrics_tpu.functional import ssim

def _np_gaussian_kernel(kernel_size, sigma):
    def g1d(n, s):
        x = np.arange(n, dtype=np.float64) - (n - 1) / 2
        k = np.exp(-(x**2) / (2 * s * s))
        return k / k.sum()

    return np.outer(g1d(kernel_size[0], sigma[0]), g1d(kernel_size[1], sigma[1]))


def _np_ssim(preds, target, data_range, kernel_size=(11, 11), sigma=(1.5, 1.5), k1=0.01, k2=0.03):
    """Mean SSIM over [B, C, H, W] float images."""
    kernel = _np_gaussian_kernel(kernel_size, sigma)
    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    vals = []
    for b in range(preds.shape[0]):
        for c in range(preds.shape[1]):
            x = preds[b, c].astype(np.float64)
            y = target[b, c].astype(np.float64)
            conv = lambda im: convolve2d(im, kernel, mode="valid")  # noqa: E731
            mu_x, mu_y = conv(x), conv(y)
            sigma_x = conv(x * x) - mu_x**2
            sigma_y = conv(y * y) - mu_y**2
            sigma_xy = conv(x * y) - mu_x * mu_y
            num = (2 * mu_x * mu_y + c1) * (2 * sigma_xy + c2)
            den = (mu_x**2 + mu_y**2 + c1) * (sigma_x + sigma_y + c2)
            vals.append(num / den)
    return np.mean(vals)


@pytest.mark.parametrize("shape", [(2, 1, 24, 24), (1, 3, 32, 20)], ids=["gray", "rgb_rect"])
@pytest.mark.parametrize("kernel_sigma", [((11, 11), (1.5, 1.5)), ((7, 5), (1.0, 2.0))], ids=["default", "asym"])
def test_ssim_functional_vs_numpy(shape, kernel_sigma):
    rng = np.random.RandomState(123)
    kernel_size, sigma = kernel_sigma
    preds = rng.rand(*shape).astype(np.float32)
    target = np.clip(preds + rng.randn(*shape).astype(np.float32) * 0.1, 0, 1)
    expected = _np_ssim(preds, target, data_range=1.0, kernel_size=kernel_size, sigma=sigma)
    ours = float(ssim(jnp.asarray(preds), jnp.asarray(target),
                      kernel_size=kernel_size, sigma=sigma, data_range=1.0))
    np.testing.assert_allclose(ours, expected, atol=1e-5)


def test_ssim_identical_images_is_one():
    rng = np.random.RandomState(124)
    x = rng.rand(1, 1, 16, 16).astype(np.float32)
    np.testing.assert_allclose(float(ssim(jnp.asarray(x), jnp.asarray(x), data_range=1.0)), 1.0, atol=1e-6)


def test_ssim_class_accumulation_vs_numpy():
    # data_range given + mean reduction → the constant-memory streaming path
    rng = np.random.RandomState(125)
    m = SSIM(data_range=1.0)
    batches = []
    for _ in range(3):
        p = rng.rand(2, 1, 24, 24).astype(np.float32)
        t = np.clip(p + rng.randn(2, 1, 24, 24).astype(np.float32) * 0.05, 0, 1)
        batches.append((p, t))
        m.update(jnp.asarray(p), jnp.asarray(t))
    expected = np.mean([_np_ssim(p, t, data_range=1.0) for p, t in batches])
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)
