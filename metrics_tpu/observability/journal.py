"""Structured event journal — the one place runtime facts become visible.

The runtime now does most of its interesting work where the user can't see
it: compiled dispatches with silent fallback ladders (``core/compiled.py``),
background sync rounds resolving on dedicated threads
(``parallel/async_sync.py``), watchdogs and channel-suspect latches
(``parallel/health.py``), auto-checkpoint cadences (``core/checkpoint.py``).
Overlap is only trustworthy when the runtime can *track* the interleaving of
compute and collectives (PAPERS.md "T3: Transparent Tracking & Triggering
for Fine-grained Overlap of Compute & Collectives") — this module is that
tracking layer: every subsystem emits typed events into one journal, and the
trace exporter (``observability/trace_export.py``) renders them as a
cross-rank timeline.

Design constraints (the hot-path contract, asserted by
``tests/observability``):

- **Off by default, ~free when off.** The recorder is a module-level
  :data:`ACTIVE` flag; every hot emission site guards with
  ``if journal.ACTIVE:`` *before* building any arguments, so the disabled
  compiled step path pays one attribute read — no allocation, no lock
  (bench config 13 asserts <2 % overhead even with the recorder ON).
- **Lock-free recording.** Each thread writes to its own pre-allocated ring
  buffer (``capacity`` events, oldest overwritten); the only lock is taken
  once per thread, at buffer registration. Background sync lanes and
  watchdog workers therefore record without ever contending with the step
  loop.
- **Never from traced code.** :func:`record` raises if called while a jax
  trace is ambient — an event emitted at trace time would fire once per
  compilation instead of once per step, silently skewing per-rank journals.
  Asserted, not assumed: emission sites live on the host side of every
  dispatch.
- **Per-rank symmetric.** Emission sites in ``parallel/`` hot paths are
  guard-free (no "emit only on this rank" branches) — enforced statically
  by metricslint's ``guarded-telemetry-emit`` rule — so LockstepWorld ranks
  record identical event sequences (``tests/observability``).

Every event carries monotonic time, rank, and step, plus kind-specific
fields (see :data:`EVENT_KINDS` — the catalog is documented in
``docs/observability.md``). Subscribers (:func:`on_event`) receive events
synchronously at the emission site — the seam for wiring degradation events
into fleet loggers — and keep emission active even while the ring buffer
recorder is disabled.
"""
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "EVENT_KINDS",
    "ACTIVE",
    "Event",
    "Subscription",
    "clear",
    "disable",
    "enable",
    "enabled",
    "events",
    "on_event",
    "record",
    "set_rank_provider",
]

#: Event-kind catalog: ``<class>.<what>`` — the class (prefix before the
#: dot) is the subscriber-filter unit (``on_event(cb, classes=("health",))``).
EVENT_KINDS: Dict[str, str] = {
    # ---- compiled eager hot path (core/compiled.py) ----------------------
    "compiled.trace": "an XLA (re)trace of a compiled update/forward program",
    "compiled.dispatch": "one compiled donated-state dispatch (the step span)",
    "compiled.fallback": "an instance permanently routed to eager, with reason",
    # ---- host sync (parallel/sync.py, parallel/async_sync.py) ------------
    "sync.gather": "a blocking health-checked host sync issuing collectives",
    "sync.plan": "a bucketed sync plan built (plan-cache miss)",
    "sync.launch": "a non-blocking round launched onto the background lane",
    "sync.resolve": "an overlapped round consumed, with staleness verdict",
    "sync.drain": "a round drained and discarded (the symmetric cancel)",
    "sync.hop": "one hop of the tiered schedule (intra gather / inter exchange / broadcast)",
    # ---- health / fault tolerance (parallel/health.py) -------------------
    "health.failure": "a typed SyncError observed at a sync boundary",
    "health.watchdog": "a sync watchdog fired on a stuck collective",
    "health.margin": "a guarded collective finished, with watchdog headroom",
    "health.channel_suspect": "the channel entered probation (suspect)",
    "health.channel_probe": "probation cooldown elapsed; one probe round allowed",
    "health.channel_readmit": "a probe round succeeded; channel readmitted",
    "health.channel_reset": "the channel forced healthy (manual reset)",
    # ---- elastic resilience (parallel/resilience.py) ---------------------
    "resilience.membership": "a negotiated membership transition (shrink/readmit)",
    "resilience.quorum": "a quorum-degraded sync negotiated over survivors",
    # ---- adaptive controller (parallel/resilience.py) --------------------
    "controller.timeout": "the controller committed a new watchdog timeout",
    "controller.schedule": "a schedule-affecting controller decision committed",
    "controller.revert": "controller decisions reverted to defaults",
    # ---- degradation (Metric._handle_sync_failure) -----------------------
    "degrade.local": "a sync failure swallowed under on_error='local'/'warn'",
    # ---- checkpointing (core/checkpoint.py) ------------------------------
    "checkpoint.save": "one rank shard atomically written",
    "checkpoint.load": "a snapshot restored (elastic folds included)",
    "checkpoint.prune": "retention removed old snapshot steps",
    "checkpoint.refused": "a snapshot refused (in-flight round / synced state)",
    # ---- compute groups (core/collections.py) ----------------------------
    "group.form": "a compute group formed (members share one state + update)",
    "group.detach": "a member copy-on-write detached from its group",
    # ---- unified execution plan (core/plan.py) ---------------------------
    "plan.build": "an ExecutionPlan built for a new state schema (cache miss)",
    "plan.hit": "an ExecutionPlan served from the unified plan cache",
    "plan.invalidate": "a state mutation invalidated an owner's plan binding",
    "plan.fused_step": "a whole-step fused program engaged (update+sync+compute)",
    "plan.tier": "a tiered (two-level) schedule derived for a schema + topology",
}

#: Fast emission gate — ``True`` while the ring-buffer recorder is enabled
#: OR any subscriber is registered. Hot call sites read this attribute
#: before building event arguments; when ``False`` an emission site costs
#: one module-attribute read and nothing else.
ACTIVE: bool = False

_DEFAULT_CAPACITY = 65536

_enabled = False
_capacity = _DEFAULT_CAPACITY
_subscribers: List["Subscription"] = []

_registry_lock = threading.Lock()
_buffers: List["_ThreadBuffer"] = []
_generation = 0
_tls = threading.local()


class Event:
    """One journal entry: monotonic time, rank, step, kind, label, fields."""

    __slots__ = ("ts", "rank", "step", "kind", "label", "fields")

    def __init__(self, ts: float, rank: int, step: int, kind: str, label: str,
                 fields: Dict[str, Any]) -> None:
        self.ts = ts
        self.rank = rank
        self.step = step
        self.kind = kind
        self.label = label
        self.fields = fields

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"Event({self.kind!r}, label={self.label!r}, rank={self.rank}, "
            f"step={self.step}, ts={self.ts:.6f}, {self.fields})"
        )

    def as_dict(self) -> Dict[str, Any]:
        return {
            "ts": self.ts,
            "rank": self.rank,
            "step": self.step,
            "kind": self.kind,
            "label": self.label,
            **self.fields,
        }


class _ThreadBuffer:
    """One thread's pre-allocated event ring. The owning thread is the only
    writer (``slots[n % capacity] = ev; n += 1`` — no lock, no allocation
    beyond the Event itself); readers snapshot after quiescing."""

    __slots__ = ("name", "slots", "n", "gen")

    def __init__(self, name: str, capacity: int, gen: int) -> None:
        self.name = name
        self.slots: List[Optional[Event]] = [None] * capacity
        self.n = 0
        self.gen = gen

    def snapshot(self) -> List[Event]:
        n, cap = self.n, len(self.slots)
        if n <= cap:
            return [e for e in self.slots[:n] if e is not None]
        start = n % cap
        ordered = self.slots[start:] + self.slots[:start]
        return [e for e in ordered if e is not None]


def _default_rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable here
        return 0


#: Rank provider seam: production reads ``jax.process_index()``; simulated
#: multi-rank worlds (thread-per-rank harnesses) install their thread-local
#: rank identity via :func:`set_rank_provider` so background-lane events
#: attribute to the fake rank that launched them.
_rank_provider: Callable[[], int] = _default_rank


def set_rank_provider(fn: Optional[Callable[[], int]]) -> Callable[[], int]:
    """Install a rank provider (``None`` restores the default); returns the
    previous one so harnesses can restore it."""
    global _rank_provider
    prev = _rank_provider
    _rank_provider = _default_rank if fn is None else fn
    return prev


def _refresh_active() -> None:
    global ACTIVE
    ACTIVE = _enabled or bool(_subscribers)


def enable(capacity: Optional[int] = None) -> None:
    """Turn the ring-buffer recorder on (idempotent). ``capacity`` sets the
    per-thread ring size (default 65536 events); changing it clears existing
    buffers."""
    global _enabled, _capacity
    if capacity is not None and capacity != _capacity:
        _capacity = int(capacity)
        clear()
    _enabled = True
    _refresh_active()


def disable() -> None:
    """Turn the recorder off. Already-recorded events remain readable via
    :func:`events` until :func:`clear`; registered subscribers keep
    receiving events (they hold :data:`ACTIVE` up on their own)."""
    global _enabled
    _enabled = False
    _refresh_active()


def enabled() -> bool:
    return _enabled


def clear() -> None:
    """Drop all recorded events (every thread's ring)."""
    global _generation
    with _registry_lock:
        _buffers.clear()
        # stale thread-local buffers (other threads') re-register lazily:
        # their generation no longer matches, so the next record() on each
        # thread allocates a fresh ring
        _generation += 1


def _thread_buffer() -> _ThreadBuffer:
    buf = getattr(_tls, "buffer", None)
    if buf is None or buf.gen != _generation:
        buf = _ThreadBuffer(threading.current_thread().name, _capacity, _generation)
        with _registry_lock:
            _buffers.append(buf)
        _tls.buffer = buf
    return buf


class Subscription:
    """Handle for one :func:`on_event` subscriber; ``close()`` detaches."""

    __slots__ = ("callback", "classes", "_closed")

    def __init__(self, callback: Callable[[Event], Any],
                 classes: Optional[frozenset]) -> None:
        self.callback = callback
        self.classes = classes
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            _subscribers.remove(self)
        except ValueError:
            pass
        _refresh_active()

    def __enter__(self) -> "Subscription":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def on_event(
    callback: Callable[[Event], Any],
    classes: Optional[Iterable[str]] = None,
) -> Subscription:
    """Subscribe ``callback`` to journal events, synchronously at emission.

    ``classes`` filters by event class (the ``<class>`` prefix of the kind,
    e.g. ``("health", "degrade")`` wires just the fault/degradation stream
    into a fleet logger); ``None`` receives everything. Registering a
    subscriber activates emission even while the ring-buffer recorder is
    disabled. The callback runs on the emitting thread (background sync
    lanes included) and must be cheap and non-raising — exceptions are
    swallowed so observability can never take down the step loop.

    Returns a :class:`Subscription`; call ``.close()`` (or use it as a
    context manager) to detach.
    """
    sub = Subscription(callback, None if classes is None else frozenset(classes))
    _subscribers.append(sub)
    _refresh_active()
    return sub


def record(kind: str, label: str = "", step: int = -1, **fields: Any) -> None:
    """Emit one event. No-op while :data:`ACTIVE` is off (hot sites guard on
    the flag themselves to skip argument construction too).

    Raises ``RuntimeError`` when called under an ambient jax trace: a
    trace-time emission would fire per compilation, not per step, skewing
    per-rank journals — the "never emit from inside traced code" contract,
    asserted here rather than assumed at the call sites.
    """
    if not ACTIVE:
        return
    from metrics_tpu.utils.checks import _tracing_active

    if _tracing_active():
        raise RuntimeError(
            f"observability.journal.record({kind!r}) called from inside traced "
            "code — events must be emitted on the host side of a dispatch, "
            "never at trace time (the emission would replay per compilation, "
            "not per step)."
        )
    ev = Event(time.monotonic(), _rank_provider(), step, kind, label, fields)
    if _enabled:
        buf = _thread_buffer()
        buf.slots[buf.n % len(buf.slots)] = ev
        buf.n += 1
    if _subscribers:
        cls = kind.partition(".")[0]
        for sub in list(_subscribers):
            if sub.classes is None or cls in sub.classes:
                try:
                    sub.callback(ev)
                except Exception:  # noqa: BLE001 - observability never raises into the step
                    pass


def events(
    kinds: Optional[Iterable[str]] = None,
    rank: Optional[int] = None,
) -> List[Event]:
    """All recorded events, merged across threads, sorted by monotonic time.

    ``kinds`` filters by exact kind or by class prefix (``"sync"`` matches
    every ``sync.*`` event); ``rank`` filters by the recorded rank. Read
    after quiescing the workload (rings are single-writer, reader-snapshot).
    """
    with _registry_lock:
        bufs = list(_buffers)
    out: List[Event] = []
    for buf in bufs:
        out.extend(buf.snapshot())
    if kinds is not None:
        wanted = set(kinds)
        out = [
            e for e in out
            if e.kind in wanted or e.kind.partition(".")[0] in wanted
        ]
    if rank is not None:
        out = [e for e in out if e.rank == rank]
    out.sort(key=lambda e: e.ts)
    return out


def event_sequence(rank: Optional[int] = None) -> List[Tuple[str, str]]:
    """The ``(kind, label)`` sequence of recorded events in time order — the
    compact form the cross-rank symmetry tests compare."""
    return [(e.kind, e.label) for e in events(rank=rank)]
