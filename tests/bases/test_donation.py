"""State-buffer donation: `jax.jit(metric.pure_update, donate_argnums=(0,))`
is the recommended hot-loop mode (accumulators update in place in HBM).

Regression guard: jnp's constant cache can alias multiple `add_state` defaults
to the SAME buffer (every `jnp.zeros(())` is one object), and donating an
aliased pytree invalidates every alias — including the metric's own defaults.
`_default_state` must therefore hand out distinct fresh buffers.
"""
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import Accuracy, AUROC, MetricCollection, StatScores

rng = np.random.RandomState(5)
_preds = rng.rand(6, 32, 10).astype(np.float32)
_target = rng.randint(0, 10, (6, 32))


def test_default_state_leaves_are_distinct_buffers():
    mc = MetricCollection(
        {"acc": Accuracy(num_classes=10), "stats": StatScores(reduce="macro", num_classes=10)}
    )
    seen = set()
    for sub in mc.init_state().values():
        for v in sub.values():
            assert id(v) not in seen, "aliased default buffers break donation"
            seen.add(id(v))


def test_donated_update_loop_and_reset():
    mc = MetricCollection(
        {"acc": Accuracy(num_classes=10), "stats": StatScores(reduce="macro", num_classes=10)}
    )
    step = jax.jit(mc.pure_update, donate_argnums=(0,))
    state = mc.init_state()
    for i in range(6):
        state = step(state, jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    vals = mc.pure_compute(state)
    acc = float(np.asarray(vals["acc"]))
    assert np.isfinite(acc)
    expected = (np.argmax(_preds, -1) == _target).mean()
    np.testing.assert_allclose(acc, expected, atol=1e-6)
    # defaults survive donation: a fresh state starts clean and works again
    state2 = mc.init_state()
    state2 = step(state2, jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    acc2 = float(np.asarray(mc.pure_compute(state2)["acc"]))
    np.testing.assert_allclose(acc2, (np.argmax(_preds[0], -1) == _target[0]).mean(), atol=1e-6)


def test_donated_catbuffer_loop():
    m = AUROC().with_capacity(512)
    p = rng.rand(4, 32).astype(np.float32)
    t = rng.randint(0, 2, (4, 32))
    m.update(jnp.asarray(p[0]), jnp.asarray(t[0]))
    m.reset()
    step = jax.jit(m.pure_update, donate_argnums=(0,))
    state = jax.jit(m.pure_update)(m.init_state(), jnp.asarray(p[0]), jnp.asarray(t[0]))
    for i in range(1, 4):
        state = step(state, jnp.asarray(p[i]), jnp.asarray(t[i]))
    from sklearn.metrics import roc_auc_score

    np.testing.assert_allclose(
        float(m.pure_compute(state)), roc_auc_score(t.reshape(-1), p.reshape(-1)), atol=1e-6
    )
