from metrics_tpu.functional.image.gradients import image_gradients  # noqa: F401
from metrics_tpu.functional.image.psnr import psnr  # noqa: F401
from metrics_tpu.functional.image.ssim import ssim  # noqa: F401
