"""metricslint fixture: a fully clean metric module — the CLI must exit 0.

Exercises the patterns the rules must NOT fire on: loop-declared states,
conditional (if/else) schema alternatives, declared shared-attr latches with
a redeclared identity, schema-only branching, and host work on untraced
(unannotated, host-side) inputs.
"""
import jax.numpy as jnp
from jax import Array

STATE_CONSTANT = "extra"


class CleanBase:
    _group_shared_attrs = ("mode",)

    def __init__(self, samplewise: bool = False):
        for s in ("tp", "fp"):
            self.add_state(s, jnp.zeros(()), dist_reduce_fx="sum")
        if samplewise:
            self.add_state("scores", [], dist_reduce_fx="cat")
        else:
            self.add_state("scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state(STATE_CONSTANT, jnp.zeros(()), dist_reduce_fx="sum")
        self.mode = None

    def add_state(self, *a, **k):
        pass

    def update_identity(self):
        return ("clean", 1)

    def update(self, preds: Array, target: Array):
        if preds.ndim == 1:  # schema branch: static under tracing
            preds = preds[None]
        self.mode = "binary"  # declared shared latch
        self.tp = self.tp + jnp.sum(preds * target)
        self.fp = self.fp + jnp.sum(preds * (1 - target))
        if isinstance(self.scores, list):
            self.scores.append(jnp.sum(preds))
        else:
            self.scores = self.scores + jnp.sum(preds)
        self.extra = self.extra + 1

    def compute(self):
        return self.tp / (self.tp + self.fp)


class CleanOverride(CleanBase):
    """overrides update AND redeclares the identity: hygiene satisfied."""

    def update_identity(self):
        return ("clean-override", 1)

    def update(self, preds: Array, target: Array):
        self.tp = self.tp + jnp.sum(preds * target)
        self.fp = self.fp + jnp.sum(preds * (1 - target))


class HostSideText:
    """unannotated host-side inputs (strings): float()/len() are legitimate
    and must not be flagged by the annotation-seeded CLI taint."""

    def __init__(self):
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")

    def add_state(self, *a, **k):
        pass

    def update(self, preds, target):
        score = float(len(preds)) / max(float(len(target)), 1.0)
        self.errors = self.errors + jnp.asarray(score)

    def compute(self):
        return self.errors
