"""Hinge module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/hinge.py`` (130 LoC).
"""
from typing import Any, Callable, Optional, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.hinge import (
    MulticlassMode,
    _hinge_compute,
    _hinge_update,
)


class Hinge(Metric):
    r"""Mean hinge loss :math:`\max(0, 1 - y \cdot \hat{y})` over the
    stream (sum + count states; one ``psum`` pair across the mesh).

    Binary input takes raw decision values ``[N]`` against targets
    {0, 1} (mapped to ±1 internally). Multiclass input ``[N, C]`` picks
    its margin per ``multiclass_mode``:

    - ``None`` / ``"crammer-singer"``: margin of the true class against
      the best wrong class (multiclass SVM loss);
    - ``"one-vs-all"``: one binary hinge per class, returned as ``[C]``.

    Args:
        squared: square each per-sample loss before averaging.
        multiclass_mode: see above.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Raises:
        ValueError: unknown ``multiclass_mode``, or target values outside
            the expected label set.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Hinge
        >>> preds = jnp.asarray([-2.2, 2.4, 0.1])
        >>> target = jnp.asarray([0, 1, 1])
        >>> hinge = Hinge()
        >>> print(round(float(hinge(preds, target)), 4))
        0.3
    """

    is_differentiable = True

    def __init__(
        self,
        squared: bool = False,
        multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("measure", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        if multiclass_mode not in (None, MulticlassMode.CRAMMER_SINGER, MulticlassMode.ONE_VS_ALL):
            raise ValueError(
                "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
                f"(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL, got {multiclass_mode}."
            )
        self.squared = squared
        self.multiclass_mode = multiclass_mode

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        measure, total = _hinge_update(preds, target, squared=self.squared, multiclass_mode=self.multiclass_mode)
        self.measure = measure + self.measure
        self.total = total + self.total

    def compute(self) -> Array:
        return _hinge_compute(self.measure, self.total)
