"""CompositionalMetric operator tests — analogue of reference
`tests/bases/test_composition.py` (559 LoC, all 30+ operators)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import CompositionalMetric, Metric
from tests.helpers.testers import DummyMetricSum


class Const(Metric):
    def __init__(self, val):
        super().__init__()
        self.add_state("v", jnp.asarray(float(val)), dist_reduce_fx="sum")

    def update(self):
        pass

    def compute(self):
        return self.v


def _c(val):
    m = Const(val)
    m._update_called = True
    return m


@pytest.mark.parametrize(
    "op, expected",
    [
        (lambda a, b: a + b, 7.0),
        (lambda a, b: a - b, 3.0),
        (lambda a, b: a * b, 10.0),
        (lambda a, b: a / b, 2.5),
        (lambda a, b: a // b, 2.0),
        (lambda a, b: a % b, 1.0),
        (lambda a, b: a ** b, 25.0),
    ],
)
def test_arithmetic_two_metrics(op, expected):
    res = op(_c(5), _c(2))
    assert isinstance(res, CompositionalMetric)
    np.testing.assert_allclose(np.asarray(res.compute()), expected)


@pytest.mark.parametrize(
    "op, expected",
    [
        (lambda a: a + 2, 7.0),
        (lambda a: 2 + a, 7.0),
        (lambda a: a - 2, 3.0),
        (lambda a: 7 - a, 2.0),
        (lambda a: a * 3, 15.0),
        (lambda a: 3 * a, 15.0),
        (lambda a: a / 2, 2.5),
        (lambda a: 10 / a, 2.0),
        (lambda a: a ** 2, 25.0),
        (lambda a: 2 ** a, 32.0),
    ],
)
def test_arithmetic_with_scalar(op, expected):
    res = op(_c(5))
    np.testing.assert_allclose(np.asarray(res.compute()), expected)


@pytest.mark.parametrize(
    "op, expected",
    [
        (lambda a, b: a == b, False),
        (lambda a, b: a != b, True),
        (lambda a, b: a < b, False),
        (lambda a, b: a <= b, False),
        (lambda a, b: a > b, True),
        (lambda a, b: a >= b, True),
    ],
)
def test_comparisons(op, expected):
    res = op(_c(5), _c(2))
    assert bool(np.asarray(res.compute())) is expected


def test_bitwise_ops():
    a, b = _c(5), _c(3)  # int semantics via int arrays
    a._state["v"] = jnp.asarray(5)
    b._state["v"] = jnp.asarray(3)
    assert int((a & b).compute()) == 1
    assert int((a | b).compute()) == 7
    assert int((a ^ b).compute()) == 6


def test_unary_ops():
    m = _c(-5)
    np.testing.assert_allclose(np.asarray(abs(m).compute()), 5.0)
    np.testing.assert_allclose(np.asarray((-m).compute()), 5.0)


def test_getitem():
    m = Const(0)
    m._state["v"] = jnp.asarray([1.0, 2.0, 3.0])
    m._update_called = True
    np.testing.assert_allclose(np.asarray(m[1].compute()), 2.0)


def test_composition_updates_both_operands():
    a, b = DummyMetricSum(), DummyMetricSum()
    comp = a + b
    comp.update(jnp.asarray(2.0))
    assert float(a.x) == 2.0
    assert float(b.x) == 2.0
    np.testing.assert_allclose(np.asarray(comp.compute()), 4.0)


def test_composition_forward():
    a, b = DummyMetricSum(), DummyMetricSum()
    comp = a + b
    v = comp(jnp.asarray(3.0))
    np.testing.assert_allclose(np.asarray(v), 6.0)


def test_composition_reset_propagates():
    a, b = DummyMetricSum(), DummyMetricSum()
    comp = a + b
    comp.update(jnp.asarray(2.0))
    comp.reset()
    assert float(a.x) == 0.0
    assert float(b.x) == 0.0


def test_nested_composition():
    res = (_c(5) + _c(2)) * 2
    np.testing.assert_allclose(np.asarray(res.compute()), 14.0)
