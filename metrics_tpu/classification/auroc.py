"""AUROC module metric.

Behavioral analogue of the reference's ``torchmetrics/classification/auroc.py``
(191 LoC).
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auroc import _auroc_compute, _auroc_update
from metrics_tpu.ops.ranking import (
    masked_binary_auroc,
    masked_multiclass_auroc,
    masked_multilabel_auroc,
)
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import AverageMethod, DataType


class AUROC(Metric):
    r"""Area under the ROC curve — the probability a random positive scores
    above a random negative (reference ``auroc.py``).

    Scores and targets accumulate across batches as "cat" states
    (``all_gather`` across the mesh at sync); the curve and its area are
    only formed at :meth:`compute`. Two accumulation layouts:

    - default: python list-of-batches (re-traces as it grows; fully
      flexible sizes);
    - :meth:`~metrics_tpu.core.metric.Metric.with_capacity`: a fixed-size
      on-device :class:`~metrics_tpu.CatBuffer` ring, making update a
      constant-shape ``dynamic_update_slice`` that stays inside one jitted
      step (the form the bench's eval loops use). Compute then uses
      masked Mann–Whitney ranking (``ops/ranking.py``) so padding rows
      never touch the statistic.

    Args:
        num_classes: number of classes for multiclass scores ``[N, C]``;
            leave ``None`` for binary ``[N]`` scores.
        pos_label: which label counts as positive for binary input
            (default 1).
        average: multiclass/multilabel reduction — ``"macro"`` averages
            per-class AUROCs, ``"weighted"`` weights them by support,
            ``"micro"`` pools all decisions (multilabel only), ``None``
            returns the per-class vector.
        max_fpr: integrate only up to this false-positive rate and rescale
            by the McClish correction (binary only).
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Raises:
        ValueError: unknown ``average``, ``max_fpr`` outside ``(0, 1]``,
            or multiclass input without ``num_classes``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUROC
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> auroc = AUROC()
        >>> print(round(float(auroc(preds, target)), 4))
        0.75
        >>> multi = AUROC(num_classes=3)
        >>> scores = jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1], [0.2, 0.2, 0.6]])
        >>> print(round(float(multi(scores, jnp.asarray([0, 1, 2]))), 4))
        1.0
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr

        allowed_average = (None, "macro", "weighted", "micro")
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode: Optional[DataType] = None
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    #: AUROC's update latches the detected input mode; a grouped dispatch
    #: copies the latch to every sibling
    _group_shared_attrs = ("mode",)

    def update_identity(self):
        """Compute-group key. ``_auroc_update`` takes no configuration —
        every AUROC instance preprocesses identically (mode detection +
        multidim flattening) — so any set of AUROC members shares one
        preds/target accumulation regardless of ``average``/``num_classes``
        (those only shape ``compute``). It does NOT share the clf-curve
        family's key: ``_precision_recall_curve_update`` reshapes/ravels
        where ``_auroc_update`` stores rows as-is, so their accumulated
        states are not provably identical."""
        return ("auroc",)

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target, mode = _auroc_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)
        if self.mode is not None and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def compute(self) -> Array:
        # Binary CatBuffer mode: exact AUROC via tie-averaged Mann-Whitney
        # ranks — every intermediate keeps the buffer's static shape, so
        # update + collective sync + compute fuse into ONE jitted program
        # (the curve path needs data-dependent unique-threshold sizes and is
        # eager-only). Identical value incl. tie handling, except the
        # degenerate single-class case: the curve path raises eagerly, this
        # path (which cannot raise under jit) returns the uninformative 0.5.
        if isinstance(self._state["preds"], CatBuffer) and self.max_fpr is None:
            preds_cb: CatBuffer = self._state["preds"]
            target_cb: CatBuffer = self._state["target"]
            if self.mode == DataType.BINARY and self.pos_label in (None, 1):
                if preds_cb.buffer is None:
                    raise ValueError("No samples to concatenate")
                # poison: an in-jit overflow overwrote rows -> NaN, not a
                # plausible wrong AUROC (cat_buffer.py `poison` contract)
                return preds_cb.poison(
                    masked_binary_auroc(preds_cb.buffer, target_cb.buffer, preds_cb.mask())
                )
            # one-vs-rest vectorized masked path: multiclass [N, C] scores vs
            # int targets, multilabel [N, C] vs [N, C] — one vmapped XLA
            # program (mdmc rows were already flattened to [N*X, C] by
            # _auroc_update)
            if (
                preds_cb.buffer is not None
                and preds_cb.buffer.ndim == 2
                and self.average != "micro"
                and self.mode in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS)
                and target_cb.buffer.ndim == 1
            ):
                return preds_cb.poison(
                    masked_multiclass_auroc(
                        preds_cb.buffer, target_cb.buffer, preds_cb.mask(), self.average
                    )
                )
            if (
                preds_cb.buffer is not None
                and preds_cb.buffer.ndim == 2
                and self.mode == DataType.MULTILABEL
                and target_cb.buffer.ndim == 2
            ):
                return preds_cb.poison(
                    masked_multilabel_auroc(
                        preds_cb.buffer, target_cb.buffer, preds_cb.mask(), self.average
                    )
                )
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _auroc_compute(
            preds, target, self.mode, self.num_classes, self.pos_label, self.average, self.max_fpr
        )
