"""One-time diagnostics: the dedupe key contract (tested per the ISSUE-8
satellite), rank gating, and the shared bench `diag` line."""
import json
import warnings

import pytest

from metrics_tpu.observability import diagnostics


@pytest.fixture(autouse=True)
def _fresh_dedupe():
    diagnostics.reset()
    yield
    diagnostics.reset()


def _caught(fn):
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        emitted = fn()
    return emitted, caught


def test_warn_once_dedupes_on_the_key():
    emitted1, caught1 = _caught(lambda: diagnostics.warn_once(("k", 1), "first"))
    emitted2, caught2 = _caught(lambda: diagnostics.warn_once(("k", 1), "second"))
    assert emitted1 is True and len(caught1) == 1 and "first" in str(caught1[0].message)
    assert emitted2 is False and caught2 == []  # same key: deduped
    assert diagnostics.seen(("k", 1))


def test_different_keys_warn_independently():
    e1, c1 = _caught(lambda: diagnostics.warn_once(("k", 1), "one"))
    e2, c2 = _caught(lambda: diagnostics.warn_once(("k", 2), "two"))
    assert e1 and e2 and len(c1) == len(c2) == 1


def test_key_is_any_hashable_tuple():
    # the conventions the runtime uses: per-instance and per-class keys
    assert diagnostics.warn_once(("compiled-fallback", 12345), "m1")
    assert diagnostics.warn_once(("compiled-fallback", 67890), "m2")
    assert not diagnostics.warn_once(("compiled-fallback", 12345), "m1 again")


def test_reset_single_key():
    diagnostics.warn_once("a", "x")
    diagnostics.warn_once("b", "x")
    diagnostics.reset("a")
    assert not diagnostics.seen("a") and diagnostics.seen("b")


def test_category_passes_through():
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        diagnostics.warn_once("cat-key", "msg", RuntimeWarning)
    assert caught and issubclass(caught[0].category, RuntimeWarning)


def test_every_rank_warns_off_rank_zero(monkeypatch):
    import metrics_tpu.utils.prints as prints

    monkeypatch.setattr(prints, "_process_index", lambda: 3)
    # rank-zero-gated: non-zero rank emits nothing but consumes the key
    emitted, caught = _caught(lambda: diagnostics.warn_once("rz", "gated"))
    assert emitted is True and caught == []
    # every_rank: non-zero rank still warns
    emitted, caught = _caught(
        lambda: diagnostics.warn_once("er", "loud", every_rank=True)
    )
    assert emitted and len(caught) == 1


def test_compiled_fallback_warns_once_per_instance():
    """The consumer contract: the compiled path's fallback diagnostic is
    keyed per dispatcher instance through this module."""
    import jax.numpy as jnp

    from metrics_tpu.core.metric import Metric

    class _L(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("t", jnp.zeros(()), dist_reduce_fx="sum")
            self.tags = []

        def update(self, x):
            self.tags.append(1)  # metricslint: disable=undeclared-state
            self.t = self.t + jnp.sum(x)

        def compute(self):
            return self.t

    m = _L(compiled_update=True)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for _ in range(3):
            m.update(jnp.ones((2,)))
    fallback_warns = [c for c in caught if "compiled eager" in str(c.message)]
    assert len(fallback_warns) == 1


def test_diag_emits_bench_convention_line(capsys):
    diagnostics.diag(config=13, note="hello", value=1.5)
    err = capsys.readouterr().err.strip()
    parsed = json.loads(err)
    assert parsed == {"diagnostic": {"config": 13, "note": "hello", "value": 1.5}}
