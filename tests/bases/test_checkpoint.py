"""Orbax-backed metric checkpointing: save mid-eval, restore, resume."""
import numpy as np
import jax.numpy as jnp
import pytest
from sklearn.metrics import roc_auc_score

from metrics_tpu import Accuracy, AUROC, MetricCollection, StatScores
from metrics_tpu.utils.checkpoint import restore_metric, save_metric

rng = np.random.RandomState(13)
_preds = rng.rand(8, 32, 10).astype(np.float32)
_target = rng.randint(0, 10, (8, 32))


def test_metric_roundtrip_resume(tmp_path):
    m = Accuracy(num_classes=10)
    for i in range(4):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    save_metric(str(tmp_path / "acc"), m)

    m2 = Accuracy(num_classes=10)
    restore_metric(str(tmp_path / "acc"), m2)
    # resume: the restored metric continues accumulating
    for i in range(4, 8):
        m2.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    expected = (np.argmax(_preds, -1) == _target).mean()
    np.testing.assert_allclose(float(m2.compute()), expected, atol=1e-6)


def test_collection_roundtrip(tmp_path):
    mc = MetricCollection(
        {"acc": Accuracy(num_classes=10), "stats": StatScores(reduce="macro", num_classes=10)}
    )
    for i in range(3):
        mc.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    vals = {k: np.asarray(v) for k, v in mc.compute().items()}
    save_metric(str(tmp_path / "mc"), mc)

    mc2 = MetricCollection(
        {"acc": Accuracy(num_classes=10), "stats": StatScores(reduce="macro", num_classes=10)}
    )
    restore_metric(str(tmp_path / "mc"), mc2)
    vals2 = mc2.compute()
    for k in vals:
        np.testing.assert_allclose(np.asarray(vals2[k]), vals[k], atol=1e-7)


def test_catbuffer_metric_roundtrip(tmp_path):
    p = rng.rand(6, 32).astype(np.float32)
    t = rng.randint(0, 2, (6, 32))
    m = AUROC().with_capacity(256)
    for i in range(3):
        m.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    save_metric(str(tmp_path / "auroc"), m)

    m2 = AUROC().with_capacity(256)
    m2.update(jnp.asarray(p[0]), jnp.asarray(t[0]))  # warm mode detection
    m2.reset()
    restore_metric(str(tmp_path / "auroc"), m2)
    for i in range(3, 6):
        m2.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    np.testing.assert_allclose(
        float(m2.compute()), roc_auc_score(t.reshape(-1), p.reshape(-1)), atol=1e-6
    )


def test_list_state_metric_roundtrip(tmp_path):
    p = rng.rand(4, 32).astype(np.float32)
    t = rng.randint(0, 2, (4, 32))
    m = AUROC()
    for i in range(4):
        m.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    val = float(m.compute())
    save_metric(str(tmp_path / "auroc_list"), m)

    m2 = AUROC()
    m2.update(jnp.asarray(p[0]), jnp.asarray(t[0]))
    m2.reset()
    restore_metric(str(tmp_path / "auroc_list"), m2)
    assert float(m2.compute()) == pytest.approx(val)


def test_persistent_flags_untouched_by_save(tmp_path):
    m = Accuracy(num_classes=10)
    m.update(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    assert not any(m._persistent.values())  # default non-persistent
    save_metric(str(tmp_path / "a"), m)
    assert not any(m._persistent.values())  # flags restored after save
    # yet the checkpoint carried the state
    m2 = Accuracy(num_classes=10)
    restore_metric(str(tmp_path / "a"), m2)
    np.testing.assert_allclose(
        float(m2.compute()), (np.argmax(_preds[0], -1) == _target[0]).mean(), atol=1e-6
    )
