"""The driver-visible multi-process host-sync dryrun, run as a test.

`__graft_entry__.dryrun_multihost` spawns 2 localhost ``jax.distributed``
processes (4 virtual CPU devices each) and pushes every state family
through the production ``compute()``-time host gather — the analogue of
the reference's ``gather_all_tensors`` path
(``torchmetrics/utilities/distributed.py:96-145``) — asserting against a
single-process oracle. Keeping it green in CI means the driver artifact
(`MULTIHOST_r*.json`) can never go stale silently.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


@pytest.mark.slow
def test_dryrun_multihost_ok(capsys):
    from __graft_entry__ import dryrun_multihost

    dryrun_multihost()
    assert "dryrun_multihost ok" in capsys.readouterr().out
