"""One execution plan — the unified schema-keyed planner (ROADMAP item 1).

Four subsystems used to plan independently off the same health-word schema
(``parallel/health.py`` ``state_schema_parts``): compute-group partitioning
(``core/collections.py``), bucketed sync layout (``parallel/bucketing.py``),
compiled-dispatch program caching (``core/compiled.py``), and the overlapped
round's epoch bookkeeping (``parallel/async_sync.py`` via ``core/metric.py``).
Each carried its own cache, its own invalidation flags, and its own fallback
ladder — so every cross-cutting feature had to thread through all four.

This module replaces the four caches with ONE store and the
``_donation_ready`` / group-detach / stale-flag patchwork with ONE
invalidation entry point:

- :class:`ExecutionPlan` — one per state schema, cached process-wide keyed
  on the exact schema string behind the health word's CRC (the full string,
  so a CRC collision can never alias two schemas onto one plan). It owns the
  bucketed-sync layout (reduce buckets, cat padding, header columns — built
  by ``parallel/bucketing.py``'s classifier, now a *view* over this store).
- :class:`PlanBinding` — the per-``Metric``/per-``MetricCollection`` view:
  the compiled dispatch program namespace (``core/compiled.py``'s
  ``CompiledDispatcher`` stores its programs here), the async round's
  ``sync_epoch`` counter, the compute-group partition flags, and the
  monotone ``generation`` bumped by every invalidation.
- :func:`plan_invalidate` — THE single invalidation path. Every state
  mutation routes here via ``Metric._mark_state_mutated`` (satellite of the
  same PR): donation ownership is revoked, the binding generation bumps,
  and a schema-changing mutation additionally marks the compute-group
  partition stale. The call is registered with metricslint's schedule pass
  (``asymmetric-schedule-decision``): an invalidation gated on the process
  index or per-rank data would legally desynchronize the planners across
  ranks, so call sites must be guard-clean — exactly like
  ``commit_schedule_decision`` in ``parallel/resilience.py``.
- :func:`compiled_step` — the whole-step fused program on top of the
  unified plan: ``update + sync_in_jit(fused=True) + compute`` traced and
  cached as ONE donated XLA program (bench config 15). Called inside the
  user's jit/pjit/``shard_map`` step it inlines into that one program, so
  XLA schedules the metric collective against metric compute and a
  per-step ``compute()`` adds zero extra dispatches (PAPERS.md "T3" is the
  exemplar: push the host-side overlap down into the compiled program).

Telemetry: the ``plan`` domain of the unified registry
(``observability/registry.py``) counts builds / cache hits / invalidations
(by reason) / fused-step engagements per owner, surfaced through
``Metric.telemetry()``; the journal records ``plan.build`` / ``plan.hit`` /
``plan.invalidate`` events when active.

``METRICS_TPU_UNIFIED_PLAN=0`` is the escape hatch: the plan store still
serves the bucketed layouts (the classification is bit-identical either
way), but bindings are not consulted, :func:`compiled_step` runs the legacy
un-fused composition (separate dispatch, sync, and compute phases), and
invalidation degrades to the bare donation-latch semantics.
"""
import os
import threading
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from metrics_tpu.observability import journal
from metrics_tpu.observability.registry import registry_of

__all__ = [
    "ExecutionPlan",
    "PlanBinding",
    "TierSchedule",
    "binding",
    "clear_plans",
    "compiled_step",
    "fused_step_refusal",
    "mark_donation_ready",
    "mark_state_mutated",
    "next_sync_epoch",
    "peek_binding",
    "plan_cache_info",
    "plan_for",
    "plan_invalidate",
    "tier_schedule_for",
    "unified_plan_enabled",
]

#: Env escape hatch: set to 0/false/off to disable the unified-plan behaviors
#: (fused whole-step programs, binding-consulted invalidation) and restore
#: the legacy per-feature semantics.
UNIFIED_PLAN_ENV = "METRICS_TPU_UNIFIED_PLAN"


def unified_plan_enabled() -> bool:
    """Default policy: unified plan on, unless the env knob opts out."""
    return os.environ.get(UNIFIED_PLAN_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


# ---------------------------------------------------------------------------
# the plan store: one ExecutionPlan per schema, process-wide
# ---------------------------------------------------------------------------


class ExecutionPlan:
    """Everything derivable from one state schema, built once and shared.

    ``schema_key`` is the exact :func:`~metrics_tpu.parallel.health.
    state_schema_parts` string (the collision-proof cache key);
    ``schema_crc`` its CRC-32 — the same value the health word carries, so a
    plan and the wire protocol can be correlated in logs. ``sync_layout`` is
    the bucketed host-sync schedule (``parallel/bucketing.py``
    :class:`~metrics_tpu.parallel.bucketing.SyncPlan`): reduce buckets, cat
    padding, header columns. Plans are immutable after construction and
    lock-protected in the store, so the async overlap layer reuses them from
    its background thread across rounds without re-planning.
    """

    __slots__ = ("schema_key", "schema_crc", "sync_layout")

    def __init__(self, schema_key: str, sync_layout: Any) -> None:
        self.schema_key = schema_key
        self.schema_crc = zlib.crc32(schema_key.encode()) & 0x7FFFFFFF
        self.sync_layout = sync_layout

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"ExecutionPlan(crc={self.schema_crc}, "
            f"buckets={getattr(self.sync_layout, 'n_buckets', 0)})"
        )


_PLANS: Dict[str, ExecutionPlan] = {}
_PLANS_LOCK = threading.Lock()
_PLAN_CACHE_MAX = 256
_plan_stats = {"hits": 0, "misses": 0, "invalidations": 0}


def clear_plans() -> None:
    """Drop every cached :class:`ExecutionPlan` and zero the store counters
    (tests / benchmarks; ``parallel.bucketing.clear_sync_plan_cache`` is the
    long-standing alias)."""
    with _PLANS_LOCK:
        _PLANS.clear()
        _TIER_SCHEDULES.clear()
        _plan_stats["hits"] = _plan_stats["misses"] = 0
        _plan_stats["invalidations"] = 0


def plan_cache_info() -> Dict[str, int]:
    with _PLANS_LOCK:
        return {"size": len(_PLANS), **_plan_stats}


def plan_for(
    state: Dict[str, Any], reductions: Dict[str, Any], owner: Any = None
) -> ExecutionPlan:
    """The (cached) :class:`ExecutionPlan` for this state schema.

    Keyed on the exact schema string the health word hashes, so any change a
    rank could legally make between syncs (a CatBuffer materializing its
    item spec, a dtype cast) keys a fresh plan, while repeated syncs of the
    same schema — every ``compute()`` of a long eval — hit the cache.
    ``owner`` (a Metric/MetricCollection) attributes the build/hit to its
    telemetry registry's ``plan`` domain.
    """
    from metrics_tpu.parallel.health import state_schema_parts

    from metrics_tpu.utils.checks import _tracing_active

    key = state_schema_parts(state, reductions)
    # trace-time lookups (pure_sync(fused=True) inside a user's jit) must
    # stay silent: journal.record refuses to fire per-compilation, and the
    # registry counters would replay-skew the same way
    host_side = not _tracing_active()
    with _PLANS_LOCK:
        plan = _PLANS.get(key)
        if plan is not None:
            _plan_stats["hits"] += 1
    if plan is not None:
        if host_side:
            if owner is not None:
                registry_of(owner).domain("plan")["cache_hits"] += 1
            if journal.ACTIVE:
                journal.record("plan.hit", schema_crc=plan.schema_crc)
        return plan
    from metrics_tpu.parallel.bucketing import _classify

    plan = ExecutionPlan(key, _classify(state, reductions, key))
    if host_side:
        if owner is not None:
            registry_of(owner).domain("plan")["builds"] += 1
        if journal.ACTIVE:
            journal.record(
                "plan.build",
                schema_crc=plan.schema_crc,
                buckets=plan.sync_layout.n_buckets,
            )
            # back-compat: the bucketed-layout event predates the plan store
            journal.record(
                "sync.plan",
                buckets=plan.sync_layout.n_buckets,
                cat_leaves=len(plan.sync_layout.cat_leaves),
            )
    with _PLANS_LOCK:
        _plan_stats["misses"] += 1
        if len(_PLANS) >= _PLAN_CACHE_MAX:
            _PLANS.pop(next(iter(_PLANS)))
        _PLANS[key] = plan
    return plan


# ---------------------------------------------------------------------------
# the tier dimension: the sync layout × the negotiated tier topology
# ---------------------------------------------------------------------------


class TierSchedule:
    """One schema's two-level collective schedule over one tier topology.

    The tier dimension ``build_sync_plan`` gained in the hierarchical-sync
    PR: an :class:`ExecutionPlan`'s bucketed layout says *what* rides each
    collective; the :class:`~metrics_tpu.parallel.tiering.TierTopology` says
    *who* participates in each hop. This object pairs them — plus the subset
    transport the hops run over — and precomputes the participant counts the
    journal and bench configs compare against the flat gather:

    - ``inter_participants`` — tier leaders only (``n_tiers``), vs.
      ``flat_participants`` (every live rank) for the flat world gather;
    - ``hops_per_bucket`` — 3 (intra gather, inter exchange, intra
      broadcast) vs. the flat path's 1, the trade the schedule makes:
      more launches on the fast hop to shrink the slow hop.

    Cached per ``(schema string, topology key)`` in the plan store's
    companion dict — a quorum shrink changes the topology key, so the same
    schema re-schedules in the new membership epoch with zero collectives.
    """

    __slots__ = ("topology", "transport", "schema_key")

    def __init__(self, topology: Any, transport: Any, schema_key: str) -> None:
        self.topology = topology
        self.transport = transport
        self.schema_key = schema_key

    @property
    def inter_participants(self) -> int:
        return self.topology.n_tiers

    @property
    def flat_participants(self) -> int:
        return len(self.topology.live)

    @property
    def hops_per_bucket(self) -> int:
        return 3

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"TierSchedule(tiers={self.topology.n_tiers}, "
            f"live={len(self.topology.live)})"
        )


_TIER_SCHEDULES: Dict[Any, TierSchedule] = {}


def tier_schedule_for(sync_plan: Any) -> Optional[TierSchedule]:
    """The tiered schedule for one bucketed layout, or ``None`` for the flat
    path (no tier map configured, no subset transport, or a degenerate
    topology — ``parallel/tiering.py`` decides; this is pure cache).

    Called once per bucketed sync by
    :func:`~metrics_tpu.parallel.bucketing.host_sync_state_bucketed`; the
    topology lookup itself is memoized on the live set, so the steady-state
    cost is two dict probes.
    """
    from metrics_tpu.parallel import tiering

    topo = tiering.active_topology()
    if topo is None or sync_plan is None:
        return None
    schema_key = getattr(sync_plan, "schema_key", "")
    key = (schema_key, topo.key)
    with _PLANS_LOCK:
        sched = _TIER_SCHEDULES.get(key)
    transport = tiering.active_tier_transport()
    if sched is not None and sched.transport is transport:
        return sched
    sched = TierSchedule(topo, transport, schema_key)
    with _PLANS_LOCK:
        if len(_TIER_SCHEDULES) >= _PLAN_CACHE_MAX:
            _TIER_SCHEDULES.clear()
        _TIER_SCHEDULES[key] = sched
    if journal.ACTIVE:
        journal.record(
            "plan.tier",
            schema_crc=zlib.crc32(schema_key.encode()) & 0x7FFFFFFF,
            tiers=topo.n_tiers,
            inter_participants=sched.inter_participants,
            flat_participants=sched.flat_participants,
        )
    return sched


# ---------------------------------------------------------------------------
# per-owner bindings: the planners' view into the plan
# ---------------------------------------------------------------------------


class PlanBinding:
    """Per-instance plan state: what the four planners used to scatter.

    - ``programs`` / ``probed`` — the compiled-dispatch program namespace
      (``CompiledDispatcher`` holds this binding and stores through it);
    - ``sync_epoch`` — the overlapped round counter (mirrored onto the
      owner's ``_sync_epoch`` attribute, which rides the health word);
    - ``generation`` — bumped by every :func:`plan_invalidate`; cached
      fused-step programs key on it so a schema change retraces.
    """

    __slots__ = ("label", "generation", "sync_epoch", "programs", "probed")

    def __init__(self, label: str = "metric") -> None:
        self.label = label
        self.generation = 0
        self.sync_epoch = 0
        self.programs: Dict[Any, Any] = {}
        self.probed: set = set()

    # bindings never copy or pickle: cached programs close over the ORIGINAL
    # owner, and the epoch/generation describe that instance alone. The
    # owner's copy paths drop the binding (``_reset_compiled_for_copy``),
    # and these guards make any stray deepcopy/pickle hand back a fresh one.
    def __deepcopy__(self, memo: dict) -> "PlanBinding":
        return PlanBinding(self.label)

    def __reduce__(self):
        return (PlanBinding, (self.label,))


def binding(owner: Any) -> PlanBinding:
    """The owner's :class:`PlanBinding` (created on first use)."""
    b = owner.__dict__.get("_plan_binding")
    if b is None:
        b = PlanBinding(type(owner).__name__)
        object.__setattr__(owner, "_plan_binding", b)
    return b


def peek_binding(owner: Any) -> Optional[PlanBinding]:
    """The owner's binding if plan machinery ever engaged, else ``None``."""
    return owner.__dict__.get("_plan_binding")


def next_sync_epoch(owner: Any) -> int:
    """Advance and return the owner's overlapped-round epoch.

    The counter lives in the plan binding (the plan owns the async round's
    epoch bookkeeping) and is mirrored onto the owner's ``_sync_epoch``
    attribute — the value the health-word header carries, which pickling
    and cloning preserve even though the binding itself never copies.
    """
    b = binding(owner)
    b.sync_epoch = max(b.sync_epoch, owner.__dict__.get("_sync_epoch", 0)) + 1
    object.__setattr__(owner, "_sync_epoch", b.sync_epoch)
    return b.sync_epoch


# ---------------------------------------------------------------------------
# the single invalidation path
# ---------------------------------------------------------------------------


def plan_invalidate(
    owner: Any,
    reason: str = "state-mutated",
    schema_changed: bool = False,
    groups_stale: bool = False,
) -> None:
    """THE invalidation entry: any state mutation that revokes plan-derived
    ownership routes here (via ``Metric._mark_state_mutated``).

    Effects — deliberately rank-symmetric and collective-free (metricslint's
    schedule pass verifies every call site commits from symmetric inputs):

    - the owner's donation latch is already cleared by the caller; this
      bumps the binding ``generation`` so cached fused-step programs and
      any other generation-keyed view re-validate;
    - ``schema_changed=True`` (``add_state``, ``with_capacity``,
      ``load_state_dict``, membership changes) additionally marks the
      compute-group partition stale for re-planning at the next dispatch;
    - ``groups_stale=True`` marks the partition stale without a schema
      change (a group detach, a reset back to defaults).

    Cheap when no plan machinery ever engaged: a metric that never compiled,
    grouped, or overlapped pays one dict lookup.
    """
    d = owner.__dict__
    if schema_changed or groups_stale:
        if "_groups_stale" in d:
            object.__setattr__(owner, "_groups_stale", True)
            if schema_changed:
                object.__setattr__(owner, "_groups_planned", False)
    b = d.get("_plan_binding")
    if b is None:
        return
    b.generation += 1
    with _PLANS_LOCK:
        _plan_stats["invalidations"] += 1
    dom = registry_of(owner).domain("plan")
    dom["invalidations"] += 1
    reasons = dom.setdefault("invalidate_reasons", {})
    reasons[reason] = reasons.get(reason, 0) + 1
    if journal.ACTIVE:
        journal.record(
            "plan.invalidate",
            label=b.label,
            reason=reason,
            schema_changed=schema_changed,
            generation=b.generation,
        )


def mark_state_mutated(
    owner: Any,
    reason: str = "state-mutated",
    schema_changed: bool = False,
    groups_stale: bool = False,
) -> None:
    """Clear the donation latch and notify the plan layer.

    The consolidation point for the historical scattered
    ``object.__setattr__(m, "_donation_ready", False)`` sites: restored /
    aliased / externally-visible state means the next compiled dispatch
    must copy before donating. The plan notification only fires on an
    actual ownership transition (latch was set) or a schema/group change —
    re-clearing an already-clear latch is the eager hot path's common case
    and stays a twice-a-dict-op no-op.
    """
    d = owner.__dict__
    owned = d.get("_donation_ready", False)
    object.__setattr__(owner, "_donation_ready", False)
    if owned or schema_changed or groups_stale:
        plan_invalidate(
            owner, reason, schema_changed=schema_changed, groups_stale=groups_stale
        )


def mark_donation_ready(owner: Any) -> None:
    """The inverse transition: a compiled dispatch's outputs are buffers the
    owner holds outright, so the next dispatch may donate them without a
    protective copy. Bookkeeping only — never an invalidation."""
    object.__setattr__(owner, "_donation_ready", True)


# ---------------------------------------------------------------------------
# the whole-step fused program (bench config 15)
# ---------------------------------------------------------------------------


def fused_step_refusal(owner: Any) -> Optional[str]:
    """Why ``owner`` cannot run the whole-step fused program (``None`` = it
    can). The conditions mirror the compiled eager path's static gate: the
    pure API must be traceable with fixed-shape state."""
    from metrics_tpu.core.collections import MetricCollection

    if isinstance(owner, MetricCollection):
        members = [m for _k, m in owner.items()]
    else:
        members = [owner]
    for m in members:
        defaults = getattr(m, "_defaults", None)
        if not defaults:
            return (
                f"{type(m).__name__} declares no states "
                "(nothing to trace into the fused step)"
            )
        for name, default in defaults.items():
            if isinstance(default, list):
                return (
                    f"{type(m).__name__} state {name!r} is a growing list — "
                    "use with_capacity() for a fixed-shape CatBuffer"
                )
        if not m._can_merge():
            return f"{type(m).__name__} state has no algebraic merge"
    return None


def _maybe_record_fused(owner: Any) -> None:
    """Count one fused-step engagement. Eager calls count per step; inside
    the user's jit the program runs as XLA with no Python to re-enter, so
    the inline path counts once per traced call skeleton instead (the
    registry bump is a plain trace-time python side effect — safe; the
    journal event stays host-side only because ``journal.record`` refuses
    to run under an ambient trace)."""
    from metrics_tpu.utils.checks import _tracing_active

    registry_of(owner).domain("plan")["fused_steps"] += 1
    if journal.ACTIVE and not _tracing_active():
        journal.record("plan.fused_step", label=type(owner).__name__)


def compiled_step(
    owner: Any,
    state: Dict[str, Any],
    args: Tuple,
    kwargs: Dict[str, Any],
    axis_name: Optional[Any] = None,
) -> Tuple[Dict[str, Any], Any]:
    """One whole metric step — ``update + in-jit sync(fused) + compute`` — as
    ONE cached, donated XLA program.

    Returns ``(new_state, values)``: ``new_state`` is the accumulated state
    (``merge``-semantics via ``pure_update``), ``values`` the cross-rank
    result computed over the synced accumulation — i.e. what a blocking
    ``sync(); compute()`` would serve, with the collective issued *inside*
    the program so XLA overlaps it with the metric compute.

    Two call modes:

    - **inside the user's jit/pjit/shard_map step** (an ambient trace is
      active): the traced composition inlines into the user's ONE program —
      the tentpole's end state. ``axis_name`` must name a mapped mesh axis.
    - **eagerly from the host**: the program is jitted with the state
      donated and cached in the owner's plan binding, keyed on the call
      skeleton and binding generation. ``axis_name`` is not supported here
      (a named-axis collective needs a surrounding shard_map/pmap); use the
      host ``sync()`` path instead.

    Donation means the caller must thread the returned ``new_state``
    forward and never reuse the ``state`` argument it passed in — the
    standard scan-carry contract. Aliased leaves (a grouped collection's
    deduped states) are detected per call and disable donation for that
    dispatch only. An update that cannot trace (data-dependent shapes, a
    python-side branch on values) is detected by the same ``eval_shape``
    probe the compiled eager path uses, and the eager composition runs
    instead — bit-identical, just separate dispatches.

    With ``METRICS_TPU_UNIFIED_PLAN=0`` the legacy composition runs instead:
    separate ``pure_update`` / ``pure_sync`` / ``pure_compute`` phases,
    un-jitted from here (the caller's own jit still applies).
    """
    import jax

    from metrics_tpu.core.compiled import rebuild_call, split_call
    from metrics_tpu.utils.checks import _tracing_active
    from metrics_tpu.utils.exceptions import MetricsTPUUserError

    reason = fused_step_refusal(owner)
    if reason is not None:
        raise MetricsTPUUserError(
            f"whole-step fused program refused for {type(owner).__name__}: "
            f"{reason}."
        )
    # plan compute groups NOW, host-side: the first pure_update would
    # otherwise build them lazily mid-trace, and the probe (rightly) refuses
    # updates that flip instance latches
    ensure_groups = getattr(type(owner), "_ensure_groups", None)
    if ensure_groups is not None:
        ensure_groups(owner)
    if not unified_plan_enabled():
        # legacy behavior: the same math as three separate phases
        new_state = owner.pure_update(state, *args, **kwargs)
        synced = (
            owner.pure_sync(new_state, axis_name=axis_name, fused=True)
            if axis_name is not None
            else new_state
        )
        return new_state, owner.pure_compute(synced)

    try:
        treedef, dyn_ix, statics, dynamic = split_call(args, kwargs)
    except TypeError:
        raise MetricsTPUUserError(
            "whole-step fused program: arguments contain unhashable "
            "non-array values; pass arrays and hashable statics only."
        ) from None

    b = binding(owner)
    key = ("step", axis_name, b.generation, treedef, dyn_ix, statics)

    def traced(st: Dict[str, Any], dyn: Any) -> Tuple[Dict[str, Any], Any]:
        a, kw = rebuild_call(treedef, dyn_ix, statics, dyn)
        new_state = owner.pure_update(st, *a, **kw)
        synced = (
            owner.pure_sync(new_state, axis_name=axis_name, fused=True)
            if axis_name is not None
            else new_state
        )
        return new_state, owner.pure_compute(synced)

    if _tracing_active():
        # inside the user's step: inline into THEIR one program; our cache
        # only needs to hand back a stable callable so the outer trace
        # machinery sees one function identity per call skeleton
        fn = b.programs.get(key)
        if fn is None:
            b.programs[key] = fn = traced
            _maybe_record_fused(owner)  # once per traced call skeleton
        return fn(state, list(dynamic))

    if axis_name is not None:
        raise MetricsTPUUserError(
            "whole-step fused program with axis_name must run inside a "
            "shard_map/pmap-mapped jit step (a named-axis collective has no "
            "meaning eagerly); call compiled_step from inside the step, or "
            "drop axis_name and use the host sync() path."
        )
    _maybe_record_fused(owner)
    leaves = jax.tree_util.tree_leaves(state)
    donate = len({id(leaf) for leaf in leaves}) == len(leaves)
    prog_key = key + (donate,)
    prog = b.programs.get(prog_key)
    if prog is None:
        from metrics_tpu.core.compiled import (
            _ensure_persistent_compile_cache,
            probe_traceable,
        )
        from metrics_tpu.core.collections import MetricCollection

        members = [owner]
        if isinstance(owner, MetricCollection):
            members.extend(m for _k, m in owner.items())
        untraceable = probe_traceable(traced, state, list(dynamic), members)
        if untraceable is not None:
            prog = untraceable  # cached refusal: eager composition from now on
        else:
            _ensure_persistent_compile_cache()
            prog = jax.jit(traced, donate_argnums=(0,) if donate else ())
        b.programs[prog_key] = prog
    if isinstance(prog, str):
        new_state = owner.pure_update(state, *args, **kwargs)
        return new_state, owner.pure_compute(new_state)
    return prog(state, list(dynamic))
