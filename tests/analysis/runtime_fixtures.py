"""Importable Metric subclasses for the probe/planner integration tests.

These live in a real module file (not a test body) because the runtime
bridge resolves a class's source via ``inspect.getsourcefile`` — classes
defined in a REPL or exec'd string stay "unknown" by design.
"""
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric


class CleanSum(Metric):
    """Straight-line declared-state update: statically verifiable clean."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)
        self.count = self.count + x.shape[0]

    def compute(self):
        return self.total / jnp.maximum(self.count, 1)


class LeakyLatch(Metric):
    """update writes an undeclared attribute: statically refutable dirty."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.last_shape = None

    def update(self, x):
        self.last_shape = x.shape
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class GroupableClean(Metric):
    """Declares an update_identity and honors the grouping contract."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update_identity(self):
        return ("groupable-clean",)

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class GroupableLeaky(Metric):
    """Declares an update_identity but latches an undeclared attribute —
    the static report must refute its grouping claim."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.rows_seen = 0

    def update_identity(self):
        return ("groupable-leaky",)

    def update(self, x):
        self.rows_seen += x.shape[0]
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class BranchyUnannotated(Metric):
    """Value-dependent python branch with UNANNOTATED params: must stay
    'unknown' (probed), never 'clean' — and never 'dirty' either, since
    eager semantics are perfectly legal."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("pos", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, x):
        if float(jnp.sum(x)) > 0:
            self.pos = self.pos + jnp.sum(x)

    def compute(self):
        return self.pos
