"""Unified execution plan (ISSUE 17 tentpole): equivalence + lifecycle suite.

The contract under test (``core/plan.py`` + the wiring in ``core/metric.py``,
``core/collections.py``, ``parallel/bucketing.py``, ``parallel/sync.py``):

- ONE schema-keyed store: ``build_sync_plan`` is a view over
  ``plan_for(...).sync_layout`` — same object identity, shared hit/miss
  counters, one ``clear_plans`` lifecycle.
- ``compiled_step`` — update + (in-jit fused sync) + compute as one cached
  donated XLA program — is bit-identical to the separate ``pure_update`` /
  ``pure_sync`` / ``pure_compute`` composition, eagerly and inside a
  ``shard_map``-mapped jit, for plain metrics and grouped collections; an
  untraceable update falls back to the eager composition with identical
  results.
- ``METRICS_TPU_UNIFIED_PLAN=0`` restores the legacy separate-phase
  composition exactly (and caches no programs).
- every donation/stale-flag invalidation routes through
  ``plan.mark_state_mutated`` / ``plan.plan_invalidate``: generation bumps,
  reasons are counted in the ``plan`` telemetry domain, epochs stay
  monotonic, bindings never pickle or deepcopy their programs.
- real two-rank payloads (``LockstepWorld``) accumulated via the unified
  path host-sync bit-identically to the legacy composition's states.
"""
import copy
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.parallel.sync as sync_mod
from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall, Specificity
from metrics_tpu.core import plan as plan_mod
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.bucketing import build_sync_plan, sync_plan_cache_info
from metrics_tpu.parallel.sync import host_sync_state
from metrics_tpu.utils.exceptions import MetricsTPUUserError
from tests.helpers.fake_world import LockstepWorld

rng = np.random.RandomState(23)
N_STEPS = 4
BATCH = 32
NUM_CLASSES = 10
PREDS = [jnp.asarray(rng.randint(0, NUM_CLASSES, (BATCH,))) for _ in range(N_STEPS)]
TARGET = [jnp.asarray(rng.randint(0, NUM_CLASSES, (BATCH,))) for _ in range(N_STEPS)]


@pytest.fixture(autouse=True)
def _fresh_plan_store():
    plan_mod.clear_plans()
    yield
    plan_mod.clear_plans()


class SumMetric(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)
        self.count = self.count + jnp.asarray(x.shape[0], jnp.int32)

    def compute(self):
        return self.total / self.count


def _collection():
    return MetricCollection(
        {
            "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
            "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
            "f1": F1(num_classes=NUM_CLASSES, average="macro"),
            "spec": Specificity(num_classes=NUM_CLASSES, average="macro"),
        }
    )


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype and x.shape == y.shape
        assert x.tobytes() == y.tobytes()


def _reference_run(owner, steps=N_STEPS):
    """The separate-phase composition the fused program must reproduce."""
    state = owner.init_state()
    values = None
    for i in range(steps):
        state = owner.pure_update(state, PREDS[i], TARGET[i])
        values = owner.pure_compute(state)
    return state, values


# ---------------------------------------------------------------------------
# one schema-keyed store
# ---------------------------------------------------------------------------


def test_build_sync_plan_is_a_view_over_the_plan_store():
    m = SumMetric()
    state, reds = m.init_state(), m._reductions
    layout = build_sync_plan(state, reds)
    plan = plan_mod.plan_for(state, reds)
    assert plan.sync_layout is layout  # same cached object, not a copy
    info = plan_mod.plan_cache_info()
    assert info["size"] == 1 and info["misses"] == 1 and info["hits"] >= 1
    # the bucketing module's legacy info surface filters the same counters
    view = sync_plan_cache_info()
    assert set(view) == {"size", "hits", "misses"}
    assert view["size"] == info["size"] and view["misses"] == info["misses"]
    plan_mod.clear_plans()
    assert plan_mod.plan_cache_info() == {
        "size": 0,
        "hits": 0,
        "misses": 0,
        "invalidations": 0,
    }


def test_schema_crc_matches_health_word_hash():
    from metrics_tpu.parallel.health import state_schema_hash

    m = SumMetric()
    plan = plan_mod.plan_for(m.init_state(), m._reductions)
    assert plan.schema_crc == state_schema_hash(m.init_state(), m._reductions)


def test_distinct_schemas_get_distinct_plans():
    a, b = SumMetric(), Accuracy(num_classes=NUM_CLASSES)
    pa = plan_mod.plan_for(a.init_state(), a._reductions)
    pb = plan_mod.plan_for(b.init_state(), b._reductions)
    assert pa is not pb and pa.schema_key != pb.schema_key
    assert plan_mod.plan_cache_info()["size"] == 2


# ---------------------------------------------------------------------------
# invalidation funnel + lifecycle
# ---------------------------------------------------------------------------


def test_mark_state_mutated_clears_latch_and_bumps_generation():
    m = SumMetric()
    binding = plan_mod.binding(m)
    g0 = binding.generation
    m._mark_donation_ready()
    assert m.__dict__["_donation_ready"] is True
    m._mark_state_mutated("state-read")
    assert m.__dict__["_donation_ready"] is False
    assert binding.generation == g0 + 1
    # not owned and no schema/group change: nothing to invalidate
    m._mark_state_mutated("state-read")
    assert binding.generation == g0 + 1
    reasons = m.telemetry()["plan"]["invalidate_reasons"]
    assert reasons.get("state-read") == 1


def test_collection_membership_changes_route_through_plan_invalidate():
    col = _collection()
    binding = plan_mod.binding(col)
    g0 = binding.generation
    col.add_metrics({"acc": Accuracy(num_classes=NUM_CLASSES)})
    assert binding.generation == g0 + 1
    assert col.__dict__["_groups_stale"] is True
    reasons = col.telemetry()["collection"]["plan"]["invalidate_reasons"]
    assert reasons.get("membership-changed", 0) >= 1


def test_sync_epoch_is_monotonic_and_mirrored():
    m = SumMetric()
    e1 = plan_mod.next_sync_epoch(m)
    e2 = plan_mod.next_sync_epoch(m)
    assert e2 == e1 + 1
    assert m.__dict__["_sync_epoch"] == e2
    assert plan_mod.binding(m).sync_epoch == e2


def test_binding_never_copies_or_pickles_its_programs():
    m = SumMetric()
    st = m.init_state()
    st, _ = m.compiled_step(st, jnp.ones((BATCH,), jnp.float32))
    assert plan_mod.peek_binding(m).programs  # something cached
    for clone in (copy.deepcopy(m), pickle.loads(pickle.dumps(m))):
        b = plan_mod.peek_binding(clone)
        assert b is None or not b.programs


# ---------------------------------------------------------------------------
# whole-step fused program ≡ separate phases
# ---------------------------------------------------------------------------


def test_compiled_step_metric_bit_identical_to_composition():
    m = SumMetric()
    state = m.init_state()
    for i in range(N_STEPS):
        state, values = m.compiled_step(state, PREDS[i].astype(jnp.float32))
    ref = SumMetric()
    rstate = ref.init_state()
    for i in range(N_STEPS):
        rstate = ref.pure_update(rstate, PREDS[i].astype(jnp.float32))
    _leaves_equal(state, rstate)
    _leaves_equal(values, ref.pure_compute(rstate))
    # ONE program cached, and it is a real jitted program (no fallback)
    progs = list(plan_mod.peek_binding(m).programs.values())
    assert len(progs) == 1 and not isinstance(progs[0], str)


def test_compiled_step_grouped_collection_bit_identical():
    col = _collection()
    state = col.init_state()
    for i in range(N_STEPS):
        state, values = col.compiled_step(state, PREDS[i], TARGET[i])
    rstate, rvalues = _reference_run(_collection())
    _leaves_equal(state, rstate)
    assert sorted(values) == sorted(rvalues)
    for k in rvalues:
        _leaves_equal(values[k], rvalues[k])
    progs = list(plan_mod.peek_binding(col).programs.values())
    assert progs and all(not isinstance(p, str) for p in progs)
    tele = col.telemetry()["collection"]["plan"]
    assert tele["fused_steps"] == N_STEPS


def test_untraceable_update_falls_back_to_eager_composition():
    m = Accuracy()  # infers num_classes from data: cannot trace
    state = m.init_state()
    for i in range(N_STEPS):
        state, values = m.compiled_step(state, PREDS[i], TARGET[i])
    rstate, rvalues = _reference_run(Accuracy())
    _leaves_equal(state, rstate)
    _leaves_equal(values, rvalues)
    progs = list(plan_mod.peek_binding(m).programs.values())
    assert progs and all(isinstance(p, str) for p in progs)  # cached refusal


def test_escape_hatch_restores_legacy_composition(monkeypatch):
    monkeypatch.setenv(plan_mod.UNIFIED_PLAN_ENV, "0")
    assert not plan_mod.unified_plan_enabled()
    col = _collection()
    state = col.init_state()
    for i in range(N_STEPS):
        state, values = col.compiled_step(state, PREDS[i], TARGET[i])
    rstate, rvalues = _reference_run(_collection())
    _leaves_equal(state, rstate)
    for k in rvalues:
        _leaves_equal(values[k], rvalues[k])
    b = plan_mod.peek_binding(col)
    assert b is None or not b.programs  # legacy path caches nothing


def test_eager_axis_name_is_a_user_error():
    m = SumMetric()
    with pytest.raises(MetricsTPUUserError):
        m.compiled_step(m.init_state(), jnp.ones((4,), jnp.float32), axis_name="w")


def test_compiled_step_inside_users_jit_with_fused_sync():
    """Inside a shard_map-mapped jit the step inlines into the user's ONE
    program and the in-jit fused sync consults the same plan store."""
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:1]), ("w",))
    col = _collection()

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P("w"), P("w", None), P("w", None)), out_specs=(P("w"), P()))
    def step(state, p, t):
        st = jax.tree_util.tree_map(lambda x: x[0], state)
        ns, vals = col.compiled_step(st, p[0], t[0], axis_name="w")
        return jax.tree_util.tree_map(lambda x: x[None], ns), vals

    state = jax.tree_util.tree_map(lambda x: x[None], col.init_state())
    for i in range(N_STEPS):
        state, values = step(state, PREDS[i][None], TARGET[i][None])
    rstate, rvalues = _reference_run(_collection())
    _leaves_equal(jax.tree_util.tree_map(lambda x: x[0], state), rstate)
    for k in rvalues:
        _leaves_equal(values[k], rvalues[k])
    # the fused in-jit sync planned through the unified store
    assert plan_mod.plan_cache_info()["size"] >= 1


# ---------------------------------------------------------------------------
# two-rank LockstepWorld: unified accumulation syncs bit-identically
# ---------------------------------------------------------------------------

WORLD = 2


@pytest.fixture
def lockstep(monkeypatch):
    world = LockstepWorld(WORLD)
    monkeypatch.setattr(jax, "process_count", lambda: world.world)
    monkeypatch.setattr(sync_mod, "_raw_process_allgather", world.allgather)
    return world


def test_lockstep_unified_vs_legacy_host_sync_bit_identical(lockstep, monkeypatch):
    """Each rank accumulates its shard through the fused whole-step program;
    the host-synced result equals the legacy separate-phase accumulation,
    bit for bit, on every rank — and fused vs per-leaf gathers agree."""

    def unified_body(rank):
        m = SumMetric()
        state = m.init_state()
        for i in range(N_STEPS):
            state, _ = m.compiled_step(state, PREDS[i].astype(jnp.float32) + rank)
        return host_sync_state(state, m._reductions, update_count=N_STEPS, timeout=0, fused=True)

    def legacy_body(rank):
        m = SumMetric()
        state = m.init_state()
        for i in range(N_STEPS):
            state = m.pure_update(state, PREDS[i].astype(jnp.float32) + rank)
        return host_sync_state(state, m._reductions, update_count=N_STEPS, timeout=0, fused=False)

    unified = lockstep.run(unified_body)
    legacy = lockstep.run(legacy_body)
    for rank in range(WORLD):
        _leaves_equal(unified[rank], legacy[rank])
    _leaves_equal(unified[0], unified[1])  # collectives are symmetric
    assert plan_mod.plan_cache_info()["hits"] >= 1  # ranks shared ONE plan
