"""Direct output tests of `_input_format_classification`.

Port of the reference's `tests/classification/test_inputs.py`: the metric
matrices validate metric-vs-sklearn where BOTH sides run inputs through the
shared formatter, so a formatter bug would cancel out — these tests pin the
formatter's outputs themselves against independently-constructed expectations
(threshold/top-k/one-hot built inline in numpy), plus the full invalid-input
and invalid-top_k ValueError grids.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import DataType
from tests.classification.inputs import (
    Input,
    _input_binary as _bin,
    _input_binary_prob as _bin_prob,
    _input_multiclass as _mc,
    _input_multiclass_prob as _mc_prob,
    _input_multidim_multiclass as _mdmc,
    _input_multidim_multiclass_prob as _mdmc_prob,
    _input_multilabel as _ml,
    _input_multilabel_multidim as _mlmd,
    _input_multilabel_multidim_prob as _mlmd_prob,
    _input_multilabel_prob as _ml_prob,
)
from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES, THRESHOLD

rng = np.random.RandomState(42)

_ml_prob_half = Input(_ml_prob.preds.astype(np.float16), _ml_prob.target)

__p = rng.rand(NUM_BATCHES, BATCH_SIZE, 2).astype(np.float32)
_mc_prob_2cls = Input(__p / __p.sum(2, keepdims=True), rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE)))

__p = rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM, EXTRA_DIM).astype(np.float32)
_mdmc_prob_many_dims = Input(
    __p / __p.sum(2, keepdims=True),
    rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM, EXTRA_DIM)),
)

__p = rng.rand(NUM_BATCHES, BATCH_SIZE, 2, EXTRA_DIM).astype(np.float32)
_mdmc_prob_2cls = Input(__p / __p.sum(2, keepdims=True), rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)))


# expectation builders (reference `test_inputs.py:58-120`), numpy/jnp flavors
def _idn(x):
    return jnp.asarray(x)


def _usq(x):
    return jnp.asarray(x)[..., None]


def _thrs(x):
    return jnp.asarray(x) >= THRESHOLD


def _rshp1(x):
    x = jnp.asarray(x)
    return x.reshape(x.shape[0], -1)


def _rshp2(x):
    x = jnp.asarray(x)
    return x.reshape(x.shape[0], x.shape[1], -1)


def _onehot(x):
    return to_onehot(jnp.asarray(x), NUM_CLASSES)


def _onehot2(x):
    return to_onehot(jnp.asarray(x), 2)


def _top1(x):
    return select_topk(jnp.asarray(x), 1)


def _top2(x):
    return select_topk(jnp.asarray(x), 2)


def _ml_preds_tr(x):
    return _rshp1(_thrs(x))


def _onehot_rshp1(x):
    return _onehot(_rshp1(x))


def _onehot2_rshp1(x):
    return _onehot2(_rshp1(x))


def _top1_rshp2(x):
    return _top1(_rshp2(x))


def _top2_rshp2(x):
    return _top2(_rshp2(x))


def _probs_to_mc_preds_tr(x):
    return _onehot2(_thrs(x))


def _mlmd_prob_to_mc_preds_tr(x):
    return _onehot2(_rshp1(_thrs(x)))


@pytest.mark.parametrize(
    "inputs, num_classes, multiclass, top_k, exp_mode, post_preds, post_target",
    [
        (_bin, None, False, None, "multi-class", _usq, _usq),
        (_bin, 1, False, None, "multi-class", _usq, _usq),
        (_bin_prob, None, None, None, "binary", lambda x: _usq(_thrs(x)), _usq),
        (_ml_prob, None, None, None, "multi-label", _thrs, _idn),
        (_ml, None, False, None, "multi-dim multi-class", _idn, _idn),
        (_ml_prob, None, None, 2, "multi-label", _top2, _rshp1),
        (_mlmd, None, False, None, "multi-dim multi-class", _rshp1, _rshp1),
        (_mc, NUM_CLASSES, None, None, "multi-class", _onehot, _onehot),
        (_mc_prob, None, None, None, "multi-class", _top1, _onehot),
        (_mc_prob, None, None, 2, "multi-class", _top2, _onehot),
        (_mdmc, NUM_CLASSES, None, None, "multi-dim multi-class", _onehot, _onehot),
        (_mdmc_prob, None, None, None, "multi-dim multi-class", _top1_rshp2, _onehot),
        (_mdmc_prob, None, None, 2, "multi-dim multi-class", _top2_rshp2, _onehot),
        (_mdmc_prob_many_dims, None, None, None, "multi-dim multi-class", _top1_rshp2, _onehot_rshp1),
        (_mdmc_prob_many_dims, None, None, 2, "multi-dim multi-class", _top2_rshp2, _onehot_rshp1),
        # half precision is promoted before thresholding
        (_ml_prob_half, None, None, None, "multi-label", lambda x: _ml_preds_tr(np.asarray(x, np.float32)), _rshp1),
        # binary as multiclass
        (_bin, None, None, None, "multi-class", _onehot2, _onehot2),
        # binary probs as multiclass
        (_bin_prob, None, True, None, "binary", _probs_to_mc_preds_tr, _onehot2),
        # multilabel as multiclass
        (_ml, None, True, None, "multi-dim multi-class", _onehot2, _onehot2),
        # multilabel probs as multiclass
        (_ml_prob, None, True, None, "multi-label", _probs_to_mc_preds_tr, _onehot2),
        # multidim multilabel as multiclass
        (_mlmd, None, True, None, "multi-dim multi-class", _onehot2_rshp1, _onehot2_rshp1),
        # multidim multilabel probs as multiclass
        (_mlmd_prob, None, True, None, "multi-label", _mlmd_prob_to_mc_preds_tr, _onehot2_rshp1),
        # multiclass prob with 2 classes as binary
        (_mc_prob_2cls, None, False, None, "multi-class", lambda x: _top1(x)[:, [1]], _usq),
        # multi-dim multi-class with 2 classes as multi-label
        (_mdmc_prob_2cls, None, False, None, "multi-dim multi-class", lambda x: _top1(x)[:, 1], _idn),
    ],
)
def test_usual_cases(inputs, num_classes, multiclass, top_k, exp_mode, post_preds, post_target):
    """Formatted (preds, target, mode) equals independently-built expectations
    (reference `test_inputs.py:126-201`), for a full batch and batch_size=1."""

    def check(preds_in, target_in):
        preds_out, target_out, mode = _input_format_classification(
            preds=jnp.asarray(preds_in),
            target=jnp.asarray(target_in),
            threshold=THRESHOLD,
            num_classes=num_classes,
            multiclass=multiclass,
            top_k=top_k,
        )
        assert mode == exp_mode
        np.testing.assert_array_equal(
            np.asarray(preds_out), np.asarray(post_preds(preds_in)).astype(np.int32)
        )
        np.testing.assert_array_equal(
            np.asarray(target_out), np.asarray(post_target(target_in)).astype(np.int32)
        )

    check(inputs.preds[0], inputs.target[0])
    check(inputs.preds[0][[0]], inputs.target[0][[0]])


def test_mode_string_and_enum_equivalence():
    _, _, mode = _input_format_classification(
        jnp.asarray(_bin_prob.preds[0]), jnp.asarray(_bin_prob.target[0]), threshold=THRESHOLD
    )
    assert mode == "binary" and mode == DataType.BINARY


def test_threshold():
    """>= threshold is inclusive (reference `test_inputs.py:205-211`)."""
    target = jnp.asarray([1, 1, 1])
    preds_probs = jnp.asarray([0.5 - 1e-5, 0.5, 0.5 + 1e-5])
    preds_out, _, _ = _input_format_classification(preds_probs, target, threshold=0.5)
    np.testing.assert_array_equal(np.asarray(preds_out).squeeze(), [0, 1, 1])


@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass",
    [
        (rng.randint(0, 2, (7,)), rng.randint(0, 2, (7,)).astype(np.float32), None, None),
        (rng.randint(0, 2, (7,)), -rng.randint(0, 2, (7,)) - 1, None, None),
        (-rng.randint(1, 3, (7,)), rng.randint(0, 2, (7,)), None, None),
        (rng.rand(7).astype(np.float32), rng.randint(2, 4, (7,)), None, False),
        (rng.randint(2, 4, (7,)), rng.randint(0, 2, (7,)), None, False),
        (rng.randint(0, 2, (8,)), rng.randint(0, 2, (7,)), None, None),
        (rng.randint(0, 2, (7,)), rng.randint(0, 2, (7, 4)), None, None),
        (rng.randint(0, 2, (7, 3)), rng.randint(0, 2, (7, 4)), None, None),
        (rng.rand(7, 3).astype(np.float32), rng.randint(2, 4, (7, 3)), None, None),
        (rng.rand(7, 3, 4, 3).astype(np.float32), rng.randint(0, 4, (7, 3, 3)), None, None),
        (rng.randint(0, 2, (7, 3, 3, 4)), rng.randint(0, 4, (7, 3, 3)), None, None),
        (_mc_prob.preds[0], rng.randint(0, 2, (BATCH_SIZE,)), None, False),
        (_mc_prob.preds[0], rng.randint(NUM_CLASSES + 1, 100, (BATCH_SIZE,)), None, None),
        (_mc_prob.preds[0], _mc_prob.target[0], NUM_CLASSES + 1, None),
        (_mc_prob.preds[0], rng.randint(NUM_CLASSES + 1, 100, (BATCH_SIZE, NUM_CLASSES)), 4, None),
        (rng.randint(0, 4, (7, 3)), rng.randint(5, 7, (7, 3)), 4, None),
        (rng.randint(0, 2, (7,)), rng.randint(0, 2, (7,)), 1, None),
        (rng.randint(0, 2, (7, 3, 3)), rng.randint(0, 2, (7, 3, 3)), 4, False),
        (rng.rand(7, 3, 3).astype(np.float32), rng.randint(0, 2, (7, 3, 3)), 4, False),
        (rng.rand(7, 3).astype(np.float32), rng.randint(0, 2, (7, 3)), 4, True),
        (rng.rand(7).astype(np.float32), rng.randint(0, 2, (7,)), 4, None),
        (rng.rand(7).astype(np.float32), rng.randint(0, 2, (7,)), 2, None),
        (rng.rand(7).astype(np.float32), rng.randint(0, 2, (7,)), 2, False),
        (rng.rand(7).astype(np.float32), rng.randint(0, 2, (7,)), 1, True),
    ],
)
def test_incorrect_inputs(preds, target, num_classes, multiclass):
    """The reference's full invalid-input grid (`test_inputs.py:219-276`)."""
    with pytest.raises(ValueError):
        _input_format_classification(
            preds=jnp.asarray(preds), target=jnp.asarray(target),
            threshold=THRESHOLD, num_classes=num_classes, multiclass=multiclass,
        )


@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass, top_k",
    [
        (_bin.preds[0], _bin.target[0], None, None, 2),
        (_bin_prob.preds[0], _bin_prob.target[0], None, None, 2),
        (_mc.preds[0], _mc.target[0], None, None, 2),
        (_ml.preds[0], _ml.target[0], None, None, 2),
        (_mlmd.preds[0], _mlmd.target[0], None, None, 2),
        (_mdmc.preds[0], _mdmc.target[0], None, None, 2),
        (_mc_prob_2cls.preds[0], _mc_prob_2cls.target[0], None, None, 0),
        (_mc_prob_2cls.preds[0], _mc_prob_2cls.target[0], None, False, 2),
        (_mc_prob.preds[0], _mc_prob.target[0], None, None, NUM_CLASSES),
        (_ml_prob.preds[0], _ml_prob.target[0], None, True, 2),
        (_ml_prob.preds[0], _ml_prob.target[0], None, True, NUM_CLASSES),
    ],
)
def test_incorrect_inputs_topk(preds, target, num_classes, multiclass, top_k):
    """Invalid top_k combinations raise (`test_inputs.py:279-312`)."""
    with pytest.raises(ValueError):
        _input_format_classification(
            preds=jnp.asarray(preds), target=jnp.asarray(target), threshold=THRESHOLD,
            num_classes=num_classes, multiclass=multiclass, top_k=top_k,
        )
