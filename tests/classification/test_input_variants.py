"""Input-variant breadth: logits, no-match/plausible edge cases, missing
classes, mdmc samplewise, and ignore_index sweeps.

Closes the round-1 gap vs the reference's `tests/classification/inputs.py`
matrix: every fixture variant drives the stat-scores family end to end
(eager + ddp-merge + sharded mesh), with hand-numpy references composed after
the shared input formatting (the existing `_sk_accuracy` strategy).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import accuracy_score as sk_accuracy

from metrics_tpu import Accuracy, Precision, Recall, StatScores
from metrics_tpu.functional import accuracy
from metrics_tpu.utils.checks import _input_format_classification
from tests.classification.inputs import (
    _input_binary_logits,
    _input_binary_prob_plausible,
    _input_multiclass_logits,
    _input_multiclass_with_missing_class,
    _input_multidim_multiclass_prob,
    _input_multilabel_logits,
    _input_multilabel_no_match,
    _input_multilabel_prob_plausible,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_micro_accuracy(preds, target):
    """Micro accuracy after the shared input formatting (flatten multilabel /
    mdmc to elements), like the reference's `_sk_accuracy`."""
    p, t, mode = _input_format_classification(preds, target, threshold=THRESHOLD)
    p, t = np.asarray(p), np.asarray(t)
    if mode == "multi-dim multi-class":
        p = np.moveaxis(p, 1, -1).reshape(-1, p.shape[1])
        t = np.moveaxis(t, 1, -1).reshape(-1, t.shape[1])
    elif mode == "multi-label":
        p, t = p.reshape(-1), t.reshape(-1)
    return sk_accuracy(y_true=t, y_pred=p)


@pytest.mark.parametrize(
    "preds, target",
    [
        (_input_binary_logits.preds, _input_binary_logits.target),
        (_input_multilabel_logits.preds, _input_multilabel_logits.target),
        (_input_multiclass_logits.preds, _input_multiclass_logits.target),
        (_input_multilabel_no_match.preds, _input_multilabel_no_match.target),
        (_input_multilabel_prob_plausible.preds, _input_multilabel_prob_plausible.target),
        (_input_binary_prob_plausible.preds, _input_binary_prob_plausible.target),
        (_input_multiclass_with_missing_class.preds, _input_multiclass_with_missing_class.target),
    ],
)
class TestVariantAccuracy(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp, preds, target):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=_sk_micro_accuracy,
            metric_args={"threshold": THRESHOLD},
        )

    @pytest.mark.nightly  # full fixture breadth; CI keeps a representative slice elsewhere
    def test_sharded(self, preds, target):
        self.run_sharded_metric_test(
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=_sk_micro_accuracy,
            metric_args={"threshold": THRESHOLD},
        )


# ---------------------------------------------------------------------------
# mdmc samplewise
# ---------------------------------------------------------------------------


def _sk_samplewise_accuracy(preds, target):
    """Per-sample micro accuracy over the extra dim, averaged over samples
    (reference mdmc_average='samplewise', `functional/.../accuracy.py`)."""
    hard = preds.argmax(1)  # [N, X]
    per_sample = (hard == target).mean(axis=1)
    return per_sample.mean()


class TestMdmcSamplewise(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_accuracy_samplewise(self, ddp):
        preds, target = _input_multidim_multiclass_prob
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=_sk_samplewise_accuracy,
            metric_args={"mdmc_average": "samplewise", "num_classes": NUM_CLASSES},
        )

    def test_accuracy_samplewise_sharded(self):
        preds, target = _input_multidim_multiclass_prob
        self.run_sharded_metric_test(
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=_sk_samplewise_accuracy,
            metric_args={"mdmc_average": "samplewise", "num_classes": NUM_CLASSES},
        )

    def test_stat_scores_samplewise_raw(self):
        """StatScores(samplewise) per-sample rows vs hand-numpy one-vs-rest."""
        preds, target = _input_multidim_multiclass_prob
        m = StatScores(reduce="micro", mdmc_reduce="samplewise", num_classes=NUM_CLASSES)
        for i in range(4):
            m.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        got = np.asarray(m.compute())  # [4*BS, 5]

        p_all = np.concatenate(list(preds[:4]), axis=0)
        t_all = np.concatenate(list(target[:4]), axis=0)
        hard = p_all.argmax(1)  # [N, X]
        x = p_all.shape[-1]
        tp = (hard == t_all).sum(axis=1)
        fp = x - tp
        fn = fp
        tn = x * (NUM_CLASSES - 2) + tp  # onehot micro: (C-1)*X - wrong
        exp = np.stack([tp, fp, tn, fn, tp + fn], axis=1)
        np.testing.assert_array_equal(got, exp)


# ---------------------------------------------------------------------------
# ignore_index
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("metric_class, metric_fn", [(Accuracy, accuracy)])
@pytest.mark.parametrize(
    "ignore_index, expected", [(None, [1.0, np.nan]), (0, [np.nan, np.nan])]
)
def test_class_not_present(metric_class, metric_fn, ignore_index, expected):
    """Reference `test_accuracy.py:327-344`: per-class score is NaN when the
    class is absent from preds AND target, or ignored."""
    preds = jnp.asarray([0, 0, 0])
    target = jnp.asarray([0, 0, 0])
    result_fn = np.asarray(
        metric_fn(preds, target, average="none", num_classes=2, ignore_index=ignore_index)
    )
    np.testing.assert_allclose(result_fn, expected, equal_nan=True)

    cl = metric_class(average="none", num_classes=2, ignore_index=ignore_index)
    cl(preds, target)
    np.testing.assert_allclose(np.asarray(cl.compute()), expected, equal_nan=True)


@pytest.mark.parametrize("ignore_index", [0, 1, NUM_CLASSES - 1])
@pytest.mark.parametrize("metric_class", [Accuracy, Precision, Recall])
def test_ignore_index_macro_drops_class(ignore_index, metric_class):
    """macro with ignore_index == macro over the remaining classes: parity
    against the same metric evaluated with average='none' and the ignored
    class masked out."""
    rng = np.random.RandomState(77)
    preds = rng.rand(256, NUM_CLASSES).astype(np.float32)
    preds /= preds.sum(1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, 256)

    kwargs = dict(num_classes=NUM_CLASSES)
    m = metric_class(average="macro", ignore_index=ignore_index, **kwargs)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    got = float(m.compute())

    m_none = metric_class(average="none", **kwargs)
    m_none.update(jnp.asarray(preds), jnp.asarray(target))
    per_class = np.asarray(m_none.compute(), dtype=np.float64)
    keep = np.ones(NUM_CLASSES, bool)
    keep[ignore_index] = False
    np.testing.assert_allclose(got, np.nanmean(per_class[keep]), atol=1e-6)


def test_select_topk_nan_row_keeps_one_hot_invariant():
    """A NaN score row must still produce exactly one prediction (lax.top_k
    ranks NaN highest); the k=1 comparison path must not zero the row."""
    from metrics_tpu.utils.data import select_topk

    x = jnp.asarray([[0.1, np.nan, 0.3], [0.5, 0.2, 0.1], [np.nan, np.nan, 0.0]])
    got = np.asarray(select_topk(x, 1))
    ref = np.zeros_like(got)
    idx = np.asarray(jax.lax.top_k(x, 1)[1][:, 0])
    ref[np.arange(3), idx] = 1
    np.testing.assert_array_equal(got, ref)
    assert (got.sum(1) == 1).all()


def test_fid_sqrtm_method_validated_at_init():
    from metrics_tpu import FID

    with pytest.raises(ValueError, match="unknown sqrtm method"):
        FID(feature=lambda x: x, feature_dim=8, streaming=True, sqrtm_method="newton")


def test_sharded_ci_representative():
    """CI twin of the nightly per-variant sharded sweep: one logit row and
    the missing-class row through the real collective."""
    t = MetricTester()
    for inp in (_input_multiclass_logits, _input_multiclass_with_missing_class):
        t.run_sharded_metric_test(
            preds=inp.preds,
            target=inp.target,
            metric_class=Accuracy,
            sk_metric=_sk_micro_accuracy,
            metric_args={"threshold": THRESHOLD},
        )
