"""MetricCollection — many metrics, one update call, one fused sync.

Behavioral analogue of the reference's ``torchmetrics/collections.py:26-235``.
TPU upgrade: :meth:`pure_forward` traces *all* member metrics' update + sync +
compute into a single XLA program, so a collection costs one fused reduction
over the mesh instead of one gather per metric (the BASELINE north star).

**Compute groups** (this module's second performance seam): members whose
state schema and update are provably identical — equal
:meth:`~metrics_tpu.Metric.state_fingerprint` AND equal
:meth:`~metrics_tpu.Metric.update_identity` — are grouped so the whole group
runs ONE update per step and owns ONE copy of state; the other members hold
views (aliases) into the shared arrays/containers. A collection of
Precision + Recall + F1 + Specificity with equal args pays for one
stat-score update instead of four, and ROC + PrecisionRecallCurve +
AveragePrecision share one preds/target buffer instead of three. Grouping is
automatic (``compute_groups=True`` default), overridable with an explicit
``compute_groups=[["p", "r"], ...]`` partition, and disabled process-wide by
``METRICS_TPU_COMPUTE_GROUPS=0``; results are bit-identical either way.
"""
import os
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.compiled import (
    CompiledDispatcher,
    compiled_update_enabled,
    compiled_warmup,
    consult_static,
    dispatch_program,
    probe_traceable,
    rebuild_call,
    split_call,
)
from metrics_tpu.core import plan as plan_mod
from metrics_tpu.core.metric import (
    _ComputeGroup,
    _ON_ERROR_MODES,
    _ON_MISSING_MODES,
    _SYNC_MODES,
    Metric,
    _copy_state_value,
    _raise_on_catbuffer_overflow,
    _reset_compiled_for_copy,
)
from metrics_tpu.observability import journal
from metrics_tpu.observability.registry import registry_of
from metrics_tpu.parallel.async_sync import (
    drain_round,
    launch_round,
    resolve_round,
)
from metrics_tpu.parallel.health import FUSED_KEY_SEP as _FUSED_KEY_SEP
from metrics_tpu.utils.data import is_traced
from metrics_tpu.utils.exceptions import MetricsTPUUserError, StaleSyncError, SyncError
from metrics_tpu.observability.diagnostics import warn_once
from metrics_tpu.utils.prints import rank_zero_warn




def _static_grouping_hazards(m: "Metric") -> List[str]:
    """metricslint validation of a compute-group candidate: reasons the
    class's update provably breaks the grouping contract (writes an attr
    that is neither an ``add_state`` state nor a declared
    ``_group_shared_attrs`` latch). Empty when clean, unresolvable, or
    pre-classification is disabled (``METRICS_TPU_ANALYSIS_PRECLASSIFY=0``).
    Deterministic from source, so every rank plans the same partition."""
    try:
        from metrics_tpu.analysis.runtime import grouping_hazards
    except Exception:  # pragma: no cover - analysis package always ships
        return []
    return grouping_hazards(m)

#: Env escape hatch: set to 0/false/off to disable compute-group formation
#: (every member then updates and owns state independently, as before).
COMPUTE_GROUPS_ENV = "METRICS_TPU_COMPUTE_GROUPS"


def compute_groups_enabled() -> bool:
    """Default grouping policy: on, unless the env knob opts the process out."""
    return os.environ.get(COMPUTE_GROUPS_ENV, "1").strip().lower() not in ("0", "false", "off", "no")


def _leaf_concrete_equal(a: Any, b: Any) -> bool:
    """Conservative bit-equality of two state leaves; traced leaves (whose
    bytes cannot be read) report unequal so grouping never guesses."""
    if a is b:
        return True
    if isinstance(a, CatBuffer) or isinstance(b, CatBuffer):
        if not (isinstance(a, CatBuffer) and isinstance(b, CatBuffer)):
            return False
        if a.capacity != b.capacity:
            return False
        for leaf_a, leaf_b in ((a.count, b.count), (a.overflowed, b.overflowed)):
            if is_traced(leaf_a) or is_traced(leaf_b):
                return False
            if np.asarray(leaf_a) != np.asarray(leaf_b):
                return False
        if (a.buffer is None) != (b.buffer is None):
            return False
        if a.buffer is None:
            return True
        return _leaf_concrete_equal(a.buffer, b.buffer)
    if isinstance(a, (list, tuple)) or isinstance(b, (list, tuple)):
        if not (isinstance(a, (list, tuple)) and isinstance(b, (list, tuple))):
            return False
        if len(a) != len(b):
            return False
        return all(_leaf_concrete_equal(x, y) for x, y in zip(a, b))
    if is_traced(a) or is_traced(b):
        return False
    a, b = np.asarray(a), np.asarray(b)
    return a.dtype == b.dtype and a.shape == b.shape and a.tobytes() == b.tobytes()


def _concrete_states_equal(a: Metric, b: Metric) -> bool:
    """Can ``b`` share ``a``'s state right now? Requires equal update counts
    (the sync header would otherwise diverge) and bit-equal state leaves."""
    if a._is_synced or b._is_synced:
        return False
    if getattr(a, "_update_count", 0) != getattr(b, "_update_count", 0):
        return False
    if sorted(a._defaults) != sorted(b._defaults):
        return False
    return all(_leaf_concrete_equal(a._state[name], b._state[name]) for name in a._defaults)


class MetricCollection(dict):
    """An ordered dict of metrics sharing a single ``update``/``forward``
    call — pass the superset of inputs once and each member picks the
    keyword arguments its ``update`` signature accepts.

    Beyond convenience, the collection is the performance seam: its
    ``pure_forward``/``pure_update`` trace every member into ONE XLA
    program, so a whole collection's update costs one fused kernel launch
    and its distributed sync batches into one collective round — the
    design BASELINE's north-star (<1% metric overhead) is built on.
    On the host path, :meth:`sync` combines every member's states into a
    single bucketed plan (``parallel/bucketing.py``): one health header
    plus one collective per dtype/fx class for the WHOLE collection —
    O(#dtypes × #fx-classes) instead of O(#metrics × #leaves) — with
    results bit-identical to the per-member loop and the same
    all-or-nothing / per-member-degradation failure semantics
    (``METRICS_TPU_FUSED_SYNC=0`` restores the per-member loop).
    ``clone(prefix=...)`` gives cheap train/val/test copies.

    **Compute groups.** With ``compute_groups=True`` (the default), members
    whose state schema (:meth:`~metrics_tpu.Metric.state_fingerprint`) and
    update (:meth:`~metrics_tpu.Metric.update_identity`) are provably
    identical share ONE update and ONE copy of state per step: the group's
    first member in collection order runs the update, and every other
    member's state leaves alias the same arrays/containers (each
    ``compute()`` still reduces independently, so results are bit-identical
    to ungrouped). The deduplication carries through the whole stack — the
    fused host sync gathers one payload per group instead of one per
    member, and ``pure_update``/``pure_sync`` trace each group's collective
    work once. With ``with_capacity(n)`` curve members, the whole group
    shares ONE :class:`~metrics_tpu.CatBuffer` — a K× memory reduction for
    a K-metric curve collection (capacities must match to group). A direct
    out-of-group ``update()``/``reset()``/``load_state_dict()`` on a single
    member copies-on-write out of its group, so divergence is always safe;
    ``on_error="local"``/``"warn"`` sync degradation falls back per member
    with the group's shared views intact, and per-member sync
    knobs (``sync_fused``, ``sync_on_error``, ``sync_timeout``,
    ``sync_strict_update_count``, custom ``dist_sync_fn``) must match
    across a group — members that differ simply stay ungrouped. Pass
    ``compute_groups=[["a","b"], ...]`` to pin the partition explicitly
    (schema mismatches raise), or ``compute_groups=False`` /
    ``METRICS_TPU_COMPUTE_GROUPS=0`` to disable.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricCollection, Precision
        >>> mc = MetricCollection({
        ...     "acc": Accuracy(num_classes=3),
        ...     "prec": Precision(num_classes=3, average="macro"),
        ... })
        >>> vals = mc(jnp.asarray([0, 2, 1]), jnp.asarray([0, 1, 1]))
        >>> print({k: round(float(v), 4) for k, v in sorted(vals.items())})
        {'acc': 0.6667, 'prec': 0.6667}

    **Checkpointing.** ``save_checkpoint``/``load_checkpoint``
    (``core/checkpoint.py``) snapshot the whole collection atomically —
    grouped members store ONE state per compute group (siblings recorded as
    aliases, re-linked on restore) — and resume elastically at a different
    world size; :meth:`checkpointer` snapshots transparently every N
    ``update``/``forward`` calls (``docs/checkpointing.md``).

    **Observability.** One :meth:`telemetry` call returns the unified
    stats snapshot for the collection AND every member — compile + sync +
    checkpoint + health counters under one schema, with delta mode and
    JSON-lines / Prometheus exporters (:meth:`compile_stats` /
    :meth:`sync_stats` remain as views over the same registry). The event
    journal (``metrics_tpu.observability``) records collection-level sync
    rounds, compute-group formation/detach and compiled fused dispatches
    alongside the member events, and exports a per-rank
    Chrome-trace/Perfetto timeline with the overlapped-sync background
    lane on its own track (``docs/observability.md``).

    Args:
        metrics: one Metric, a list/tuple of Metrics, or a dict name->Metric.
        prefix / postfix: added to every key in the output dict.
        compute_groups: ``True`` (default) groups schema/update-identical
            members automatically; a list of key-lists pins the groups
            explicitly; ``False`` disables grouping.
    """

    #: Collection-level analogue of :attr:`Metric.sync_mode`: ``"overlap"``
    #: makes ``compute()`` resolve ONE collection-level background round
    #: (launched a compute-interval earlier over the combined bucketed
    #: payload) and launch the next, so the whole collection's periodic
    #: ``compute()`` costs ~0 host wall-clock. Plain attribute
    #: (``mc.sync_mode = "overlap"``) or the ``sync_mode=`` ctor kwarg.
    sync_mode: str = "blocking"

    #: What a stale collection-round resolve serves — one policy for the
    #: whole round (all-or-nothing application); see
    #: :attr:`Metric.staleness_policy`.
    staleness_policy: str = "snapshot"

    #: Collection-level analogue of :attr:`Metric.sync_precision`: opt-in
    #: bf16/int8 encoding of the combined bucketed payload's inter-tier
    #: (slow-hop) wire when a tier map is configured. One value for the
    #: whole combined gather — the health word's precision column verifies
    #: every rank agrees.
    sync_precision: Optional[str] = None

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, Sequence[Sequence[str]]] = True,
        sync_mode: str = "blocking",
        staleness_policy: str = "snapshot",
        sync_precision: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        if sync_mode not in _SYNC_MODES:
            raise MetricsTPUUserError(
                f"`sync_mode` must be one of {_SYNC_MODES}, got {sync_mode!r}"
            )
        self.sync_mode = sync_mode
        from metrics_tpu.parallel.async_sync import validate_staleness_policy

        self.staleness_policy = validate_staleness_policy(staleness_policy)
        from metrics_tpu.parallel.quantize import validate_sync_precision

        self.sync_precision = validate_sync_precision(sync_precision)
        self._inflight_round = None
        self._inflight_owners: Optional[List[Tuple[str, Metric, List[Metric]]]] = None
        self._inflight_counts: Optional[Dict[str, int]] = None
        self._sync_epoch = 0
        self._overlap_warned = False
        if not (
            isinstance(compute_groups, bool)
            or (
                isinstance(compute_groups, (list, tuple))
                and all(
                    isinstance(grp, (list, tuple)) and all(isinstance(k, str) for k in grp)
                    for grp in compute_groups
                )
            )
        ):
            raise MetricsTPUUserError(
                "`compute_groups` must be a bool or a list of lists of metric "
                f"keys, got {compute_groups!r}"
            )
        self._compute_groups_arg = compute_groups
        self._groups_planned = False
        self._groups_stale = True
        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def add_metrics(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = type(metric).__name__
                    if name in self:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")
        # membership changed: re-plan compute groups at the next dispatch
        # (the partition is plan state — one invalidation path, core/plan.py)
        plan_mod.plan_invalidate(self, "membership-changed", schema_changed=True)

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def items(self, keep_base: bool = True) -> Iterable[Tuple[str, Metric]]:  # type: ignore[override]
        """Default keeps base keys (dict protocol — deepcopy/pickle iterate
        this); pass ``keep_base=False`` for the prefixed/postfixed view."""
        if keep_base:
            return super().items()
        return [(self._set_name(k), v) for k, v in super().items()]

    def keys(self, keep_base: bool = True) -> Iterable[str]:  # type: ignore[override]
        if keep_base:
            return super().keys()
        return [self._set_name(k) for k in super().keys()]

    # ---------------- compute-group planner ----------------

    @property
    def compute_group_keys(self) -> List[List[str]]:
        """The live compute groups as lists of member keys (empty when
        grouping is disabled or no members qualify). Builds lazily."""
        self._ensure_groups()
        groups: Dict[int, List[str]] = {}
        order: List[int] = []
        for k, m in super().items():
            g = m._compute_group
            if g is None:
                continue
            if id(g) not in groups:
                order.append(id(g))
            groups.setdefault(id(g), []).append(k)
        return [groups[i] for i in order if len(groups[i]) >= 2]

    def _iter_group_objects(self) -> Iterator[_ComputeGroup]:
        seen: set = set()
        for m in super().values():
            g = m._compute_group
            if g is not None and id(g) not in seen:
                seen.add(id(g))
                yield g

    def _ensure_groups(self) -> None:
        """Build (or rebuild) the compute-group partition.

        Members group when they have (a) an equal, non-``None``
        ``update_identity`` — the family's promise that their updates are
        the same computation — (b) an equal ``state_fingerprint`` (identical
        ``add_state`` schemas), (c) equal sync configuration (a group syncs
        through one member, so its knobs must speak for all), and (d)
        bit-equal current state (a member updated out of band keeps its own
        state). Same construction + same feed history → same groups on
        every rank. The state-equality condition means rank-LOCAL
        divergence (direct per-member updates, per-rank checkpoints) can
        legally produce different partitions per rank; the sync layer is
        built to survive that — the fused path's combined header verifies
        the partition-dependent key set across ranks before any payload
        gather (symmetric ``StateDivergenceError`` on mismatch), and the
        per-member loop never dedupes, so its collective schedule is
        partition-independent.
        """
        if self._groups_planned and not self._groups_stale:
            return
        self._groups_planned = True
        self._groups_stale = False
        self._dissolve_groups()
        arg = self._compute_groups_arg
        if arg is False or not compute_groups_enabled():
            return
        members = list(super().items())
        if len(members) < 2:
            return
        if isinstance(arg, (list, tuple)):
            self._link_explicit_groups(arg, dict(members))
            return
        # a metric object registered under several keys updates once per key
        # (historical semantics) — it must never group with itself
        occurrences: Dict[int, int] = {}
        for _k, m in members:
            occurrences[id(m)] = occurrences.get(id(m), 0) + 1
        buckets: Dict[Any, List[Tuple[str, Metric]]] = {}
        order: List[Any] = []
        for k, m in members:
            if m._is_synced or occurrences[id(m)] > 1:
                continue
            ident = m._effective_update_identity()
            if ident is None:
                continue
            hazards = _static_grouping_hazards(m)
            if hazards:
                # the class declares an update_identity but its update
                # provably latches an undeclared attribute: grouping would
                # leave siblings with stale latches. Keep it solo (results
                # stay correct, the dedup is lost) and say why, once.
                warn_once(
                    ("group-static-hazard", type(m)),
                    f"{type(m).__name__} declares update_identity() but is "
                    "excluded from compute groups: " + "; ".join(hazards[:3])
                    + ". Declare the attribute(s) in _group_shared_attrs "
                    "(or with add_state) to restore grouping.",
                    UserWarning,
                )
                continue
            key = (ident, m.state_fingerprint()) + self._sync_config_key(m)
            if key not in buckets:
                order.append(key)
            buckets.setdefault(key, []).append((k, m))
        for key in order:
            bucket = buckets[key]
            if len(bucket) < 2:
                continue
            # split by current state: only members that are bit-equal right
            # now may share (out-of-band updates keep a member solo)
            subgroups: List[List[Tuple[str, Metric]]] = []
            for k, m in bucket:
                for sg in subgroups:
                    if _concrete_states_equal(sg[0][1], m):
                        sg.append((k, m))
                        break
                else:
                    subgroups.append([(k, m)])
            for sg in subgroups:
                if len(sg) >= 2:
                    self._link_group(sg)

    @staticmethod
    def _sync_config_key(m: Metric) -> Tuple:
        """The per-member configuration a compute group must agree on beyond
        the state schema: a group syncs and merges through ONE member, so
        its transport/degradation/strictness knobs (and any ``merge_states``
        override) speak for every sibling."""
        return (
            repr(m.process_group),
            None if m.dist_sync_fn is None else id(m.dist_sync_fn),
            getattr(m, "sync_on_error", "raise"),
            bool(getattr(m, "sync_strict_update_count", False)),
            getattr(m, "sync_fused", None),
            getattr(m, "sync_timeout", None),
            id(type(m).merge_states),
        )

    def _link_explicit_groups(
        self, spec: Sequence[Sequence[str]], by_key: Dict[str, Metric]
    ) -> None:
        seen: set = set()
        for group_keys in spec:
            keys = list(group_keys)
            for k in keys:
                if k not in by_key:
                    raise MetricsTPUUserError(
                        f"compute_groups override names unknown metric {k!r}; "
                        f"collection keys are {sorted(by_key)}"
                    )
                if k in seen:
                    raise MetricsTPUUserError(
                        f"compute_groups override lists metric {k!r} in more than one group"
                    )
                seen.add(k)
            if len(keys) < 2:
                continue
            ms = [by_key[k] for k in keys]
            collection_occurrences: Dict[int, int] = {}
            for m in by_key.values():
                collection_occurrences[id(m)] = collection_occurrences.get(id(m), 0) + 1
            if any(collection_occurrences[id(m)] > 1 for m in ms):
                # covers both two group keys holding one object AND an object
                # grouped under one key while also registered under another:
                # once-per-key update semantics cannot coexist with group
                # dispatch deduplication for the same instance
                raise MetricsTPUUserError(
                    f"compute_groups override groups {keys}, but at least one of those "
                    "metrics is registered under several collection keys — a metric "
                    "registered under several keys updates once per key and cannot "
                    "join a compute group."
                )
            for k, m in zip(keys, ms):
                hazards = _static_grouping_hazards(m)
                if hazards:
                    # an explicit override is the user's promise, but the
                    # static report *refutes* it with a concrete attr+line:
                    # shared dispatch would silently skip that latch on
                    # every non-dispatching sibling — refuse loudly.
                    raise MetricsTPUUserError(
                        f"compute_groups override groups {keys}, but metricslint "
                        f"statically refutes {k!r} ({type(m).__name__}) as a group "
                        "member: " + "; ".join(hazards[:3]) + ". Declare the "
                        "attribute(s) in _group_shared_attrs (or with add_state), "
                        "or remove the metric from the explicit group."
                    )
            fp0 = ms[0].state_fingerprint()
            cfg0 = self._sync_config_key(ms[0])
            for k, m in zip(keys[1:], ms[1:]):
                if m.state_fingerprint() != fp0:
                    raise MetricsTPUUserError(
                        f"compute_groups override groups {keys}, but {k!r} declares a "
                        f"different state schema than {keys[0]!r}: compute-group members "
                        "must have identical `add_state` declarations (name/shape/dtype/"
                        "default/dist_reduce_fx)."
                    )
                if self._sync_config_key(m) != cfg0:
                    raise MetricsTPUUserError(
                        f"compute_groups override groups {keys}, but {k!r} is configured "
                        f"differently from {keys[0]!r} (process_group / dist_sync_fn / "
                        "sync_on_error / sync_strict_update_count / sync_fused / "
                        "sync_timeout / merge_states override): a group syncs through one "
                        "member, so these knobs must match across the group."
                    )
                if not _concrete_states_equal(ms[0], m):
                    raise MetricsTPUUserError(
                        f"compute_groups override groups {keys}, but the current states of "
                        f"{keys[0]!r} and {k!r} differ — group members must start from "
                        "identical (e.g. freshly reset) state."
                    )
            self._link_group(list(zip(keys, ms)))

    def _link_group(self, sg: List[Tuple[str, Metric]]) -> None:
        metrics = [m for _, m in sg]
        group = _ComputeGroup(metrics)
        for m in metrics:
            object.__setattr__(m, "_compute_group", group)
        self._relink_group(group)
        if journal.ACTIVE:
            journal.record(
                "group.form", label=type(metrics[0]).__name__,
                members=len(metrics), keys=",".join(k for k, _ in sg),
            )

    def _relink_group(self, group: _ComputeGroup, source: Optional[Metric] = None) -> None:
        """Point every member's state leaves at ``source``'s objects (zero
        copies — arrays are immutable, containers are shared in place) and
        propagate the family's declared update side-effect attributes."""
        if not group.members:
            return
        if source is None:
            source = group.members[0]
        for m in group.members:
            if m is source:
                continue
            for name in source._state:
                m._state[name] = source._state[name]
            for name, d in source._defaults.items():
                # an update materializes the dispatching member's CatBuffer
                # DEFAULT (item spec fixed, see _wrap_update); propagate it so
                # sibling fingerprints stay equal (groups survive reset) and
                # sibling init_state() keeps a stable pytree structure
                if (
                    isinstance(d, CatBuffer)
                    and d.buffer is not None
                    and isinstance(m._defaults.get(name), CatBuffer)
                    and m._defaults[name].buffer is None
                ):
                    m._defaults[name] = d
            for attr in type(m)._group_shared_attrs:
                if hasattr(source, attr):
                    setattr(m, attr, getattr(source, attr))

    def _relink_groups(self) -> None:
        for group in self._iter_group_objects():
            self._relink_group(group)

    def _dissolve_groups(self) -> None:
        for group in list(self._iter_group_objects()):
            for m in group.members:
                object.__setattr__(m, "_compute_group", None)
            group.members.clear()

    def _break_group(self, group: _ComputeGroup) -> None:
        """Disband a group whose dispatch raised mid-mutation: every member
        takes private copies of whatever state it currently sees and leaves
        the group, so no later ``_relink_group`` can clobber a sibling with
        the failed member's partial state. This reproduces the ungrouped
        failure semantics — the member that was mid-update keeps its
        partial/wiped state (exactly what a solo ``Metric.forward`` leaves
        behind), untouched siblings keep their accumulation. ``reset()``
        re-plans the partition, so the group re-forms on the next epoch."""
        members = list(group.members)
        group.members.clear()
        for m in members:
            object.__setattr__(m, "_compute_group", None)
            m._state = {k: _copy_state_value(v) for k, v in m._state.items()}
        if journal.ACTIVE:
            journal.record(
                "group.detach", label="MetricCollection",
                members=len(members), reason="dispatch-failure",
            )
        plan_mod.plan_invalidate(self, "group-dispatch-failure", groups_stale=True)

    # ---------------- forward / update / compute ----------------

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        self._ensure_groups()
        out: Dict[str, Any] = {}
        group_values: Dict[int, Dict[int, Any]] = {}
        for k, m in super().items():
            g = m._compute_group
            if g is None:
                out[self._set_name(k)] = m(*args, **m._filtered_kwargs(kwargs))
            else:
                if id(g) not in group_values:
                    group_values[id(g)] = self._group_forward(g, m, args, kwargs)
                out[self._set_name(k)] = group_values[id(g)][id(m)]
        ckpt = getattr(self, "_auto_checkpointer", None)
        if ckpt is not None:
            ckpt.after_update(self)
        return out

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:  # type: ignore[override]
        self._ensure_groups()
        # the collection-level compiled step: every compiled-eligible
        # dispatch unit (solo member or compute-group leader) updates inside
        # ONE donated-state XLA program; whatever it could not take stays on
        # the per-member loop below (which may still compile per member)
        handled: set = self._maybe_compiled_collection_update(args, kwargs)
        for m in self.values():
            if id(m) in handled:
                continue
            g = m._compute_group
            if g is None:
                m.update(*args, **m._filtered_kwargs(kwargs))
            else:
                handled.update(id(p) for p in g.members)
                self._group_update(g, m, args, kwargs)
        ckpt = getattr(self, "_auto_checkpointer", None)
        if ckpt is not None:
            ckpt.after_update(self)

    # ---------------- compiled eager hot path ----------------

    def _compiled_dispatcher(self) -> CompiledDispatcher:
        disp = self.__dict__.get("_compiled")
        if disp is None:
            # bound to the telemetry registry's "compile" domain, exactly
            # like Metric's — one storage behind compile_stats()/telemetry()
            disp = CompiledDispatcher(
                "MetricCollection", registry_of(self).domain("compile")
            )
            self.__dict__["_compiled"] = disp
        return disp

    def compile_stats(self) -> Dict[str, Any]:
        """Compiled-eager observability for the collection and its members.

        ``{"collection": {...}, "members": {key: {...}}}`` — the collection
        entry counts the fused multi-unit programs (one XLA dispatch updating
        every eligible compute-group leader together, plus the compiled group
        ``forward`` programs); member entries count their own solo programs
        and record per-instance fallback reasons. See
        :meth:`Metric.compile_stats` (like it, a view over the unified
        telemetry registry — prefer :meth:`telemetry` in new code).
        """
        from metrics_tpu.core.compiled import compile_stats_view

        coll = compile_stats_view(registry_of(self).domain("compile"))
        return {"collection": coll, "members": {k: m.compile_stats() for k, m in super().items()}}

    def telemetry(self, delta: bool = False) -> Dict[str, Any]:
        """The unified observability snapshot for the collection and every
        member: ``{"collection": {schema, compile, sync, checkpoint, health,
        process}, "members": {key: <member telemetry>}}`` — one call returns
        the compile + sync + checkpoint + health counters for everything
        this collection runs (see :meth:`Metric.telemetry`;
        ``delta=True`` returns per-counter change since the previous delta
        call on each registry)."""
        from metrics_tpu.core.compiled import compile_stats_view

        reg = registry_of(self)
        extra = {"compile": compile_stats_view(reg.domain("compile"))}
        coll = reg.delta(extra) if delta else reg.snapshot(extra)
        return {
            "collection": coll,
            "members": {k: m.telemetry(delta=delta) for k, m in super().items()},
        }

    def _compiled_units(self) -> List[Tuple[str, Metric, Tuple[Metric, ...]]]:
        """One ``(key, leader, members)`` triple per dispatch unit — solo
        members stand alone, compute groups dispatch through their leader."""
        units: List[Tuple[str, Metric, Tuple[Metric, ...]]] = []
        seen: set = set()
        for k, m in super().items():
            g = m._compute_group
            if g is None:
                units.append((k, m, (m,)))
            elif id(g) not in seen:
                seen.add(id(g))
                units.append((k, m, tuple(g.members)))
        return units

    def _maybe_compiled_collection_update(self, args: Tuple, kwargs: Dict[str, Any]) -> set:
        """Fuse all compiled-eligible units' updates into ONE XLA dispatch.

        Returns the set of handled member ids (empty when nothing fused).
        With fewer than two eligible units there is nothing to fuse beyond
        what the member-level path already compiles — the per-member loop
        (whose dispatch hits the same program cache as a direct
        ``m.update()``) is left to it, so the same step is never compiled
        twice. A fallback-triggering member simply stays on the eager loop:
        results are identical, the fused program just shrinks around it.
        """
        if not compiled_update_enabled():
            return set()
        eligible: List[Tuple[str, Metric, Tuple[Metric, ...]]] = []
        force = False
        for k, m, members in self._compiled_units():
            knob = getattr(m, "compiled_update", None)
            if knob is False:
                continue
            disp = m._compiled_dispatcher()
            if "update" in disp.fallback or not m._compiled_static_ok("update", disp):
                continue
            force = force or knob is True
            eligible.append((k, m, members))
        if len(eligible) < 2:
            return set()
        coll_disp = self._compiled_dispatcher()
        coll_disp.steps_seen += 1
        if "update" in coll_disp.fallback:
            return set()
        if not force and coll_disp.steps_seen <= compiled_warmup():
            return set()
        if coll_disp.storming("update"):
            return set()
        try:
            treedef, dyn_ix, statics, dynamic = split_call(args, kwargs)
        except TypeError:
            coll_disp.mark_fallback("update", "update arguments contain unhashable non-array values")
            return set()
        pairs = [(k, m) for k, m, _ in eligible]
        key = ("update", tuple(k for k, _ in pairs), treedef, dyn_ix, statics)

        def build():
            def traced(states, dyn):
                a, kw = rebuild_call(treedef, dyn_ix, statics, dyn)
                return {
                    k: m.pure_update(states[k], *a, **m._filtered_kwargs(kw)) for k, m in pairs
                }

            return traced

        if not coll_disp.probed(key):
            # metricslint pre-classification, member-attributed: statically
            # dirty members mark THEIR OWN fallback with the definition-time
            # diagnostic (the next step's eligibility pass fuses the rest
            # under a new key); an all-clean roster skips the fused probe.
            dirty_members = 0
            all_clean = True
            for _k, m in pairs:
                m_verdict, m_detail = consult_static([(m, ("update",))])
                if m_verdict == "dirty":
                    m._compiled_dispatcher().mark_fallback("update", m_detail)
                    dirty_members += 1
                all_clean = all_clean and m_verdict == "clean"
            if dirty_members:
                return set()
            if all_clean:
                coll_disp.mark_probed(key)
        if not coll_disp.probed(key):
            reason = probe_traceable(
                build(),
                {k: dict(m._state) for k, m in pairs},
                dynamic,
                [m for _, m in pairs],
            )
            if reason is not None:
                # attribute the failure: probe each unit alone, so one
                # untraceable member marks only ITSELF fallback — the next
                # step's eligibility pass then fuses the remaining units
                # under a new key (the fused program shrinks around it)
                culprits = 0
                for k, m in pairs:

                    def solo(state, dyn, _m=m):
                        a, kw = rebuild_call(treedef, dyn_ix, statics, dyn)
                        return _m.pure_update(state, *a, **_m._filtered_kwargs(kw))

                    solo_reason = probe_traceable(solo, dict(m._state), dynamic, [m])
                    if solo_reason is not None:
                        m._compiled_dispatcher().mark_fallback("update", solo_reason)
                        culprits += 1
                if culprits == 0:
                    # no individual culprit: the combination itself failed —
                    # only then is the collection-level program hopeless
                    coll_disp.mark_fallback("update", reason)
                return set()
            coll_disp.mark_probed(key)
        if any(p._is_synced for _, _, members in eligible for p in members):
            raise MetricsTPUUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync``?"
            )
        prog = coll_disp.program(key, build)
        for _, m, _ in eligible:
            m._ensure_donation_safe()
        states = {k: dict(m._state) for k, m in pairs}
        handled_ok, new_states = dispatch_program(coll_disp, "update", prog, states, dynamic)
        if not handled_ok:
            return set()
        handled: set = set()
        for k, m, members in eligible:
            st = m._state
            ns = new_states[k]
            for name in st:
                st[name] = ns[name]
            m._mark_donation_ready()
            try:
                _raise_on_catbuffer_overflow(st, type(m).__name__)
            except MetricsTPUUserError:
                # mirror the eager failure semantics: a raising group update
                # disbands the group so no later relink clobbers siblings
                if m._compute_group is not None:
                    self._break_group(m._compute_group)
                raise
            m._update_count = getattr(m, "_update_count", 0) + 1
            m._update_called = True
            m._computed = None
            for p in members:
                handled.add(id(p))
                if p is m:
                    continue
                p._computed = None
                p._update_called = True
                p._update_count = m._update_count
            g = m._compute_group
            if g is not None:
                self._relink_group(g, m)
            for p in members:
                ckpt = getattr(p, "_auto_checkpointer", None)
                if ckpt is not None:
                    ckpt.after_update(p)
        return handled

    def _maybe_compiled_group_forward(
        self, group: _ComputeGroup, source: Metric, args: Tuple, kwargs: Dict[str, Any]
    ) -> Optional[Dict[int, Any]]:
        """Compiled group-level ``forward``: ONE donated-state XLA program
        runs the group's single update on a fresh batch state, every
        member's batch-local compute (XLA CSEs the shared stat work), and
        the one merge back into the shared accumulation. Returns
        ``{id(member): batch_value}`` or ``None`` (eager path)."""
        knob = getattr(source, "compiled_update", None)
        if knob is False or not compiled_update_enabled():
            return None
        members = list(group.members)
        if any(getattr(p, "compiled_update", None) is False for p in members):
            return None
        if any(p.dist_sync_on_step or getattr(p, "check_finite", False) for p in members):
            return None
        disp = source._compiled_dispatcher()
        if "forward" in disp.fallback:
            return None
        if knob is not True and disp.steps_seen <= compiled_warmup():
            return None
        if not source._compiled_static_ok("forward", disp):
            return None
        coll_disp = self._compiled_dispatcher()
        member_keys = tuple(k for k, m in super().items() if m in members)
        fkind = "forward[" + ",".join(member_keys) + "]"
        if fkind in coll_disp.fallback:
            return None
        if coll_disp.storming(fkind):
            return None
        try:
            treedef, dyn_ix, statics, dynamic = split_call(args, kwargs)
        except TypeError:
            coll_disp.mark_fallback(fkind, "forward arguments contain unhashable non-array values")
            return None
        key = (fkind, treedef, dyn_ix, statics)
        on_step = [p for p in members if p.compute_on_step]
        # forward's update precedes the batch computes: mark every member
        # updated before tracing, so the compute wrapper's not-yet-updated
        # warning cannot fire from the trace (the eager path sets this at
        # the same point via the inner update)
        for p in members:
            p._update_called = True

        def build():
            def traced(state, dyn):
                a, kw = rebuild_call(treedef, dyn_ix, statics, dyn)
                batch = source.pure_update(
                    source._batch_default_state(), *a, **source._filtered_kwargs(kw)
                )
                values = tuple(p.pure_compute(batch) for p in on_step)
                return source.merge_states(state, batch), values

            return traced

        if not coll_disp.probed(key):
            # metricslint pre-classification for the group forward: the one
            # program traces source's update + merge and EVERY on-step
            # member's compute, so all of those must be statically clean to
            # skip the probe; a dirty verdict falls back with the
            # definition-time diagnostic.
            verdict, detail = consult_static(
                [(source, ("update", "merge"))] + [(p, ("compute",)) for p in on_step]
            )
            if verdict == "dirty":
                coll_disp.mark_fallback(fkind, detail)
                return None
            if verdict != "clean":
                reason = probe_traceable(build(), dict(source._state), dynamic, members)
                if reason is not None:
                    coll_disp.mark_fallback(fkind, reason)
                    return None
            coll_disp.mark_probed(key)
        prog = coll_disp.program(key, build)
        source._ensure_donation_safe()
        handled_ok, out = dispatch_program(coll_disp, fkind, prog, dict(source._state), dynamic)
        if handled_ok is False:
            return None
        new_state, values = out
        st = source._state
        for name in st:
            st[name] = new_state[name]
        source._mark_donation_ready()
        try:
            _raise_on_catbuffer_overflow(st, type(source).__name__)
        except MetricsTPUUserError:
            self._break_group(group)  # mirror the eager forward failure path
            raise
        source._update_count = getattr(source, "_update_count", 0) + 1
        for p in members:
            p._update_called = True
            p._computed = None
            p._update_count = source._update_count
        self._relink_group(group, source)
        out: Dict[int, Any] = {}
        values_it = iter(values)
        for p in members:
            if p.compute_on_step:
                p._forward_cache = next(values_it)
                out[id(p)] = p._forward_cache
            else:
                out[id(p)] = None
        for p in members:
            ckpt = getattr(p, "_auto_checkpointer", None)
            if ckpt is not None:
                ckpt.after_update(p)
        return out

    def _group_update(
        self, group: _ComputeGroup, source: Metric, args: Tuple, kwargs: Dict[str, Any]
    ) -> None:
        """One update for the whole group: ``source`` (the group's first
        member in collection order) runs it, siblings re-alias its result."""
        if any(p._is_synced for p in group.members if p is not source):
            raise MetricsTPUUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync``?"
            )
        group.dispatching = True
        try:
            source.update(*args, **source._filtered_kwargs(kwargs))
        except BaseException:
            # the update failed mid-mutation: disband the group so the next
            # dispatch cannot re-link siblings onto the partial state
            self._break_group(group)
            raise
        finally:
            group.dispatching = False
        for p in group.members:
            if p is source:
                continue
            p._computed = None
            p._update_called = True
            p._update_count = source._update_count
        self._relink_group(group, source)
        # the dispatched update ran on `source`, whose own hook fired inside
        # _wrap_update; a checkpointer attached to a SIBLING must fire too —
        # its accumulation advanced just the same (shared state)
        for p in group.members:
            if p is not source:
                ckpt = getattr(p, "_auto_checkpointer", None)
                if ckpt is not None:
                    ckpt.after_update(p)

    def _group_forward(
        self, group: _ComputeGroup, source: Metric, args: Tuple, kwargs: Dict[str, Any]
    ) -> Dict[int, Any]:
        """Group-level ``forward``: one update on a fresh batch state, then
        every member computes ITS batch value from the shared batch state,
        then one merge back into the shared accumulation — the single-update
        forward of ``Metric.forward``, paid once per group instead of once
        per member. Returns ``{id(member): batch_value}``.
        """
        if any(p._is_synced for p in group.members):
            raise MetricsTPUUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync``?"
            )
        if all(not p.compute_on_step for p in group.members):
            self._group_update(group, source, args, kwargs)
            return {id(p): None for p in group.members}
        compiled = self._maybe_compiled_group_forward(group, source, args, kwargs)
        if compiled is not None:
            return compiled
        accumulated = {k: _copy_state_value(v) for k, v in source._state.items()}
        can_merge = source._can_merge()
        # the inner updates run on a transient batch state: a member-level
        # auto-checkpointer must not snapshot it (Metric.forward makes the
        # same guarantee for the solo path)
        object.__setattr__(source, "_ckpt_suppress", True)
        try:
            source._restore(source._batch_default_state())
            group.dispatching = True
            try:
                source.update(*args, **source._filtered_kwargs(kwargs))
            finally:
                group.dispatching = False
            for p in group.members:
                if p is not source:
                    p._update_called = True
                    p._computed = None
            self._relink_group(group, source)  # members see the batch state
            values: Dict[int, Any] = {}
            for p in group.members:
                if not p.compute_on_step:
                    values[id(p)] = None
                    continue
                p._to_sync = p.dist_sync_on_step
                p._computed = None
                try:
                    p._forward_cache = p.compute()
                finally:
                    p._to_sync = True
                p._computed = None
                values[id(p)] = p._forward_cache
            batch_state = {k: _copy_state_value(v) for k, v in source._state.items()}
            if can_merge:
                source._restore(source.merge_states(accumulated, batch_state))
            else:
                # non-mergeable state: replay the reference's double-update path
                source._restore(accumulated)
                group.dispatching = True
                try:
                    source.update(*args, **source._filtered_kwargs(kwargs))
                finally:
                    group.dispatching = False
        except BaseException:
            # a failed forward leaves the mid-dispatch member on whatever
            # partial state the failure produced (ungrouped semantics);
            # disband the group so no later re-link clobbers the siblings
            self._break_group(group)
            raise
        finally:
            object.__setattr__(source, "_ckpt_suppress", False)
        for p in group.members:
            if p is not source:
                p._update_count = source._update_count
        self._relink_group(group, source)
        # fire every member's checkpointer (suppressed during the transient
        # batch-state phase above): each member's accumulation advanced
        for p in group.members:
            ckpt = getattr(p, "_auto_checkpointer", None)
            if ckpt is not None:
                ckpt.after_update(p)
        return values

    def compute(self) -> Dict[str, Any]:
        """Compute every member's value.

        With a collection-level overlapped round in flight — or
        ``sync_mode="overlap"`` set — this resolves/launches through ONE
        collection sync (members then compute on the applied views with
        zero per-member collectives) and restores the local accumulations
        on the way out; otherwise each member syncs itself as before.
        """
        overlap_auto = getattr(self, "sync_mode", "blocking") == "overlap"
        if self.__dict__.get("_inflight_round") is not None or (
            overlap_auto and self._overlap_eligible(None)
        ):
            self.sync()
            try:
                return {self._set_name(k): m.compute() for k, m in super().items()}
            finally:
                self.unsync()
        return {self._set_name(k): m.compute() for k, m in super().items()}

    def reset(self) -> None:
        round_, _owners, _counts = self._clear_inflight()
        if round_ is not None:
            # the accumulation is being discarded, but the round's
            # collectives were launched on every rank: drain symmetrically
            drain_round(round_)
            self._sync_stats_dict()["cancelled"] += 1
        groups = list(self._iter_group_objects())
        for g in groups:
            g.dispatching = True
        try:
            for m in self.values():
                m.reset()
        finally:
            for g in groups:
                g.dispatching = False
                self._relink_group(g)
        # every member is back on its defaults: re-plan at the next dispatch
        # so members that had copy-on-write detached can rejoin their group
        plan_mod.plan_invalidate(self, "reset", groups_stale=True)

    def __getstate__(self) -> Dict[str, Any]:
        # consulted by BOTH pickle and copy.deepcopy (via __reduce_ex__):
        # an in-flight round's future holds thread locks and cannot be
        # serialized or copied — drain it symmetrically first (fold-back
        # preserves every member's accumulation)
        self._cancel_overlap()
        # the plan binding's cached programs close over THIS instance and
        # don't serialize — the copy re-creates a fresh binding lazily
        return {k: v for k, v in self.__dict__.items() if k != "_plan_binding"}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        # a copy/unpickle carries a fresh, unbound dispatcher — drop it and
        # zero the registry's compile domain so the lazily re-created one
        # binds to clean counters (mirrors Metric.__setstate__)
        _reset_compiled_for_copy(self)

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        # an in-flight round's future cannot deepcopy: drain symmetrically
        # first (fold-back preserves every member's accumulation)
        self._cancel_overlap()
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self.values():
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        """Full per-member snapshot — group members each serialize the shared
        state under their own prefix, so the checkpoint loads identically
        into a grouped OR ungrouped (``METRICS_TPU_COMPUTE_GROUPS=0``)
        collection."""
        out: Dict[str, Any] = {}
        for k, m in super().items():
            out.update(m.state_dict(prefix=f"{k}."))
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = False) -> None:
        """Per-member load. Members leave their compute groups while loading
        (each may be handed divergent state); the partition is re-planned at
        the next dispatch, re-grouping exactly the members whose loaded
        states are bit-equal.

        With ``strict=True`` the checkpoint must cover every member's every
        declared state and carry no keys outside them: a typed
        :class:`~metrics_tpu.utils.exceptions.StateDictMismatchError`
        listing the missing and unexpected keys is raised *before* any
        member mutates (unexpected keys are judged collection-wide — a key
        belonging to one member is never "unexpected" to another)."""
        if strict:
            declared = {
                f"{k}.{name}" for k, m in super().items() for name in m._defaults
            }
            missing = sorted(declared - set(state_dict))
            unexpected = sorted(set(state_dict) - declared)
            if missing or unexpected:
                from metrics_tpu.utils.exceptions import StateDictMismatchError

                raise StateDictMismatchError(
                    "load_state_dict(strict=True) for MetricCollection: "
                    f"missing keys {missing}, unexpected keys {unexpected}. "
                    "Nothing was loaded."
                )
        for k, m in super().items():
            m.load_state_dict(state_dict, prefix=f"{k}.")
        plan_mod.plan_invalidate(self, "load-state-dict", schema_changed=True)

    def checkpointer(
        self,
        directory: str,
        *,
        every_n_updates: int = 1,
        keep_last: Optional[int] = None,
        rank: Optional[int] = None,
        world: Optional[int] = None,
    ) -> Any:
        """Context manager: periodic preemption-safe snapshots from
        ``update``/``forward`` — the collection-level analogue of
        :meth:`Metric.checkpointer`. Grouped members snapshot ONE state per
        compute group (siblings are recorded as aliases and re-link on
        restore). See ``docs/checkpointing.md``."""
        from metrics_tpu.core.checkpoint import MetricCheckpointer

        return MetricCheckpointer(
            self,
            directory,
            every_n_updates=every_n_updates,
            keep_last=keep_last,
            rank=rank,
            world=world,
        )

    # ---------------- host sync (fault-tolerance aware) ----------------

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        on_error: Optional[str] = None,
        on_missing: Optional[str] = None,
        timeout: Optional[float] = None,
        blocking: Optional[bool] = None,
    ) -> None:
        """Host-sync every member, threading the fault-tolerance knobs.

        Default transport is the **collection-fused** path: all members'
        states combine into one key-prefixed dict and sync through a single
        bucketed plan (``parallel/bucketing.py``) — one health header plus
        one collective per dtype/fx class for the WHOLE collection, instead
        of O(#metrics × #leaves). Compute groups shrink the plan further:
        only one member per group contributes its (shared) state, so the
        header's count/length columns and the collective payloads scale
        with the number of *unique* states, not members.
        ``METRICS_TPU_FUSED_SYNC=0`` (or any member's ``sync_fused=False``)
        restores the per-member loop, which deliberately syncs EVERY member
        — including group siblings — one at a time: each member gathers its
        own (pre-sync, still-aliased) local state, so values are identical,
        and the collective count per rank stays a function of the member
        count alone. Deduping here would make the collective schedule
        depend on the group partition, which depends on state bytes — and
        a rank whose members diverged out-of-band (direct updates,
        per-rank checkpoints) would then issue fewer collectives than its
        peers and desynchronize the channel. The fused path CAN dedupe
        safely because its one combined header verifies the (partition-
        dependent) key set across ranks before any payload moves.

        Failure semantics are preserved from the per-member protocol:

        - all-or-nothing under ``on_error="raise"`` — the fused sync raises
          before any member state is touched (no rollback needed); on the
          per-member loop, already-synced members are rolled back before
          the error propagates, so the collection is never left half-synced;
        - under ``"local"``/``"warn"`` a failed fused sync falls back to the
          per-member loop so each member degrades *independently* — healthy
          members still report global values while sick ones keep local
          state (``Metric.sync`` swallows the error per member); a degraded
          group keeps its shared views intact (state is untouched) and every
          sibling is marked degraded together.

        ``blocking=False`` launches ONE collection-level **non-blocking**
        round instead (``parallel/async_sync.py``): the combined
        (group-deduped, key-prefixed) states snapshot into the round, every
        member restarts on fresh delta buffers, and the fused header +
        bucketed payload gather on a background thread. The next
        ``sync()``/``compute()``/``state_dict()`` resolves the round and
        applies it to every member all-or-nothing (a mid-application
        failure mutates nothing); :attr:`sync_mode` ``"overlap"`` pipelines
        this automatically. A failed resolve degrades exactly like a failed
        blocking fused sync: all-``"raise"`` raises after every member's
        full local accumulation is restored, otherwise the per-member
        *blocking* loop reruns so each member degrades (or recovers)
        independently.

        ``on_missing`` (default: the members' ``sync_on_missing``) selects
        the missing-rank policy, exactly as on :meth:`Metric.sync`: under
        ``"quorum"`` the fused transport itself re-negotiates a shrunken
        membership and retries over the survivor set
        (``parallel/resilience.py``) before any failure surfaces here;
        under ``"local"`` a missing-rank failure falls back to the
        per-member loop (each member degrades to local-only) even when
        every member's ``on_error`` is ``"raise"``.
        """
        if on_error is not None and on_error not in _ON_ERROR_MODES:
            raise MetricsTPUUserError(
                f"`on_error` must be one of {_ON_ERROR_MODES}, got {on_error!r}"
            )
        if on_missing is not None and on_missing not in _ON_MISSING_MODES:
            raise MetricsTPUUserError(
                f"`on_missing` must be one of {_ON_MISSING_MODES}, got {on_missing!r}"
            )
        self._ensure_groups()
        overlap_auto = getattr(self, "sync_mode", "blocking") == "overlap"
        if blocking is None:
            blocking = not overlap_auto
        failed_resolve = False
        if should_sync and self.__dict__.get("_inflight_round") is not None:
            try:
                self._resolve_overlap(
                    on_error=on_error,
                    timeout=timeout,
                    relaunch=not blocking,
                    on_missing=on_missing,
                )
                return
            except SyncError as err:
                modes = [
                    on_error if on_error is not None else getattr(m, "sync_on_error", "raise")
                    for m in self.values()
                ]
                degrades = not all(
                    mode == "raise" for mode in modes
                ) or self._missing_degrades(err, on_missing)
                registry_of(self).count_error(err, degraded=degrades)
                if journal.ACTIVE:
                    journal.record(
                        "health.failure", label="MetricCollection",
                        error=type(err).__name__, phase="resolve",
                    )
                if not degrades:
                    raise  # every member's local accumulation was restored first
                # degradation requested somewhere: every member holds its
                # restored local state — rerun the per-member BLOCKING loop
                # so each applies its own on_error (and a healthy channel
                # lets healthy members recover with a fresh gather)
                failed_resolve = True
                blocking = True
        if should_sync and not blocking and dist_sync_fn is None:
            if self._overlap_eligible(distributed_available):
                self._launch_overlap(
                    timeout=timeout, serve_local=overlap_auto, on_missing=on_missing
                )
                return
            if not self.__dict__.get("_overlap_warned", False):
                self._overlap_warned = True
                rank_zero_warn(
                    "MetricCollection cannot overlap its sync (a member has a "
                    "custom dist_sync_fn/process_group, non-mergeable state, "
                    "strict update counts, or the fused path is disabled) — "
                    "falling back to the blocking path.",
                    UserWarning,
                )
            blocking = True
        if should_sync and dist_sync_fn is None and self._fused_sync_eligible(distributed_available):
            try:
                self._sync_fused(timeout=timeout, on_missing=on_missing)
                return
            except SyncError as err:
                modes = [
                    on_error if on_error is not None else getattr(m, "sync_on_error", "raise")
                    for m in self.values()
                ]
                degrades = not all(
                    mode == "raise" for mode in modes
                ) or self._missing_degrades(err, on_missing)
                registry_of(self).count_error(err, degraded=degrades)
                if journal.ACTIVE:
                    journal.record(
                        "health.failure", label="MetricCollection",
                        error=type(err).__name__, phase="fused",
                    )
                if not degrades:
                    raise  # nothing was synced: all-or-nothing holds trivially
                # degradation requested somewhere: re-run per member so each
                # applies its own on_error (healthy members still get global
                # values; the verify outcome is identical on every rank, so
                # all ranks fall back together and collectives stay aligned)
        # per-member loop: every member syncs itself, grouped or not. A
        # synced member _restores gathered COPIES into its own dict, so a
        # later sibling still gathers the group's pre-sync local values —
        # no double counting — and the collective count per rank never
        # depends on the (state-dependent) group partition.
        synced: List[Metric] = []
        try:
            for m in self.values():
                m.sync(
                    dist_sync_fn=dist_sync_fn,
                    should_sync=should_sync,
                    distributed_available=distributed_available,
                    on_error=on_error,
                    on_missing=on_missing,
                    timeout=timeout,
                    blocking=blocking,
                )
                if m._is_synced:
                    synced.append(m)
        except Exception:
            for m in synced:
                m.unsync()
            raise
        if failed_resolve and any(m._sync_degraded for m in self.values()):
            # count the round degraded only when a member actually ended on
            # local-only state — a blocking rerun that fully recovered every
            # member is a recovery, not a degradation
            self._sync_stats_dict()["degraded"] += 1

    def _missing_degrades(self, err: SyncError, on_missing: Optional[str]) -> bool:
        """Does the ``on_missing="local"`` policy intercept this failure?
        True when ``err`` is the missing-rank class (watchdog timeout /
        membership-divergent header) and the explicit override — or some
        member's ``sync_on_missing`` — asks for local-only degradation on
        lost peers. The collection then reruns the per-member loop instead
        of hard-raising, so each member applies its own policy."""
        from metrics_tpu.parallel.resilience import is_missing_rank_error

        if not is_missing_rank_error(err):
            return False
        return any(
            (on_missing if on_missing is not None else getattr(m, "sync_on_missing", "raise"))
            == "local"
            for m in self.values()
        )

    def _effective_on_missing(self, on_missing: Optional[str]) -> str:
        """The missing-rank policy a COMBINED (fused/overlapped) round runs
        under: the explicit override, else the members' unanimous
        ``sync_on_missing``, else ``"raise"`` (a split vote cannot be
        honored by one shared transport — the per-member loop can)."""
        if on_missing is not None:
            return on_missing
        modes = {getattr(m, "sync_on_missing", "raise") for m in self.values()}
        return modes.pop() if len(modes) == 1 else "raise"

    def _fused_sync_eligible(self, distributed_available: Optional[Callable]) -> bool:
        """Can this collection sync through one combined bucketed plan?

        Requires the built-in transport on every member (no ``dist_sync_fn``,
        no ``process_group``), a distributed world, no member already synced
        (the per-member loop raises the proper "already synced" error), and
        the fused knob on (env default; any member's ``sync_fused=False``
        opts the whole collection out).
        """
        from metrics_tpu.parallel.bucketing import fused_sync_enabled

        members = list(self.values())
        if not members or not fused_sync_enabled():
            return False
        if any(
            m.dist_sync_fn is not None
            or m.process_group is not None
            or m._is_synced
            # a member-level overlapped round owns that member's
            # accumulation: a fused gather of its live (delta) state would
            # move the wrong bytes — the per-member loop resolves it instead
            or m.__dict__.get("_inflight") is not None
            or getattr(m, "sync_fused", None) is False
            # strict update-count checking is per member: the combined
            # header carries one summed count column, which would escalate
            # strictness onto non-strict members (and opposite-direction
            # skews could cancel in the sum) — strict members keep the
            # per-member loop's exact semantics
            or getattr(m, "sync_strict_update_count", False)
            for m in members
        ):
            return False
        if any(_FUSED_KEY_SEP in key for key in self.keys()):
            return False
        for m in members:
            avail = (
                distributed_available
                if distributed_available is not None
                else m.distributed_available_fn
            )
            if not avail():
                return False
        return True

    def _combined_payload(
        self,
        owners: List[Tuple[str, Metric, List[Metric]]],
        state_of: Callable[[Metric], Dict[str, Any]],
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """The key-prefixed combined state + reductions the fused paths
        (blocking ``_sync_fused`` AND overlapped rounds) gather — one
        definition, so the two transports can never disagree on payload
        schema."""
        combined: Dict[str, Any] = {}
        reductions: Dict[str, Any] = {}
        for key, m, _peers in owners:
            for name, value in state_of(m).items():
                combined[f"{key}{_FUSED_KEY_SEP}{name}"] = value
                reductions[f"{key}{_FUSED_KEY_SEP}{name}"] = m._reductions.get(name)
        return combined, reductions

    def _effective_member_timeout(self, timeout: Optional[float]) -> Optional[float]:
        member_timeouts = [
            t for m in self.values() if (t := getattr(m, "sync_timeout", None)) is not None
        ]
        return timeout if timeout is not None else (
            min(member_timeouts) if member_timeouts else None
        )

    def _sync_state_owners(self) -> List[Tuple[str, Metric, List[Metric]]]:
        """One ``(key, metric, group_siblings)`` triple per *unique* state:
        compute-group siblings share their representative's gathered result
        instead of contributing duplicate payloads."""
        owners: List[Tuple[str, Metric, List[Metric]]] = []
        seen_groups: set = set()
        for key, m in super().items():
            g = m._compute_group
            if g is None:
                owners.append((key, m, []))
            elif id(g) not in seen_groups:
                seen_groups.add(id(g))
                owners.append((key, m, [p for p in g.members if p is not m]))
        return owners

    def _sync_fused(
        self, timeout: Optional[float] = None, on_missing: Optional[str] = None
    ) -> None:
        """One bucketed plan over every *unique* member state (compute-group
        siblings dedupe to one payload; the header's count/length columns
        shrink accordingly).

        The combined header's ``update_count`` column carries the SUM of
        unique-state counts — a best-effort skew indicator only (opposite-
        direction member skews can cancel), which is why strict-mode
        members are excluded from fused eligibility and keep the exact
        per-member check. Raises the typed ``SyncError`` before any member
        state is mutated — all-or-nothing without rollback.
        """
        from metrics_tpu.parallel.sync import host_sync_state

        owners = self._sync_state_owners()
        combined, reductions = self._combined_payload(owners, lambda m: m._state)
        # attribute the combined schema's plan build/hit to the collection's
        # registry (host_sync_state consults the store with no owner in scope)
        from metrics_tpu.core.plan import plan_for

        plan_for(combined, reductions, owner=self)
        synced = host_sync_state(
            combined,
            reductions,
            update_count=sum(getattr(m, "_update_count", 0) for _, m, _p in owners),
            timeout=self._effective_member_timeout(timeout),
            metric_name=f"MetricCollection[{', '.join(self.keys())}]",
            fused=True,
            on_missing=self._effective_on_missing(on_missing),
            sync_precision=getattr(self, "sync_precision", None),
            stats=self._sync_stats_dict(),
        )
        # snapshot each owner's pre-sync state only now: the sync never
        # mutates its inputs, and a failed attempt (the common case the
        # on_error fallback exists for) must not pay for full state copies
        for key, m, peers in owners:
            m._cache = {k: _copy_state_value(v) for k, v in m._state.items()}
            m._sync_degraded = False
            m._restore({name: synced[f"{key}{_FUSED_KEY_SEP}{name}"] for name in m._state})
            m._is_synced = True
            for p in peers:
                p._cache = {k: _copy_state_value(v) for k, v in m._cache.items()}
                p._sync_degraded = False
                # the synced leaves alias the owner's (and the caches hold the
                # pre-sync arrays): donation must copy first — mirrors what
                # Metric._restore guarantees for the owner
                p._mark_state_mutated("fused-sync")
                for name in m._state:
                    p._state[name] = m._state[name]
                p._is_synced = True

    # ---------------- overlapped (non-blocking) collection sync ----------------

    def _sync_stats_dict(self) -> Dict[str, Any]:
        return registry_of(self).domain("sync")

    def sync_stats(self) -> Dict[str, Any]:
        """Overlapped-sync observability, mirroring :meth:`compile_stats`:
        the ``collection`` entry counts collection-level rounds (one round =
        one fused header + bucketed payload for ALL members), member entries
        count their own standalone rounds. See :meth:`Metric.sync_stats`
        (like it, a view over the unified telemetry registry — prefer
        :meth:`telemetry` in new code)."""
        coll = dict(registry_of(self).domain("sync"))
        return {"collection": coll, "members": {k: m.sync_stats() for k, m in super().items()}}

    def _overlap_eligible(self, distributed_available: Optional[Callable]) -> bool:
        """Can this collection launch one combined non-blocking round? The
        fused-path conditions plus: every member's state must merge
        algebraically (the post-snapshot delta folds back via
        ``merge_states``) and no round may already be in flight."""
        if self.__dict__.get("_inflight_round") is not None:
            return False
        if not self._fused_sync_eligible(distributed_available):
            return False
        return all(m._overlap_refusal() is None for m in self.values())

    def _launch_combined(
        self,
        owners: List[Tuple[str, Metric, List[Metric]]],
        state_of: Callable[[Metric], Dict[str, Any]],
        timeout: Optional[float],
        on_missing: Optional[str] = None,
    ) -> None:
        """The one launch path for a collection round: build the combined
        key-prefixed payload from ``state_of(owner)`` (live state on a fresh
        launch, the unsync cache on a pipeline relaunch), launch, and record
        the in-flight bookkeeping."""
        combined, reductions = self._combined_payload(owners, state_of)
        counts = {key: getattr(m, "_update_count", 0) for key, m, _peers in owners}
        # warm + attribute the combined schema's plan on the launching
        # thread (the background gather consults the store ownerless)
        plan_mod.plan_for(combined, reductions, owner=self)
        # epoch bookkeeping lives with the plan binding (mirrored onto
        # ``_sync_epoch``, the header column every rank cross-checks)
        plan_mod.next_sync_epoch(self)
        round_ = launch_round(
            combined,
            reductions,
            update_count=sum(counts.values()),
            epoch=self._sync_epoch,
            metric_name=f"MetricCollection[{', '.join(self.keys())}]",
            timeout=self._effective_member_timeout(timeout),
            fused=True,
            on_missing=self._effective_on_missing(on_missing),
            sync_precision=getattr(self, "sync_precision", None),
            stats=self._sync_stats_dict(),
        )
        self._inflight_round = round_
        self._inflight_owners = owners
        self._inflight_counts = counts
        for m in self.values():
            object.__setattr__(m, "_inflight_collection", self)
        self._sync_stats_dict()["launched"] += 1

    def _launch_overlap(
        self,
        timeout: Optional[float] = None,
        serve_local: bool = False,
        on_missing: Optional[str] = None,
    ) -> None:
        """Launch ONE background round over the combined (group-deduped,
        key-prefixed) member states and restart every member on fresh delta
        buffers — the collection-level double buffer. ``serve_local`` (the
        ``sync_mode="overlap"`` pipeline's first interval) serves each
        member its just-snapshotted accumulation as this read's value."""
        owners = self._sync_state_owners()
        snapshots = {key: dict(m._state) for key, m, _peers in owners}  # move
        self._launch_combined(owners, lambda m: m._state, timeout, on_missing=on_missing)
        # the round owns the snapshot containers; members restart on fresh
        # defaults (group siblings re-link onto ONE fresh state)
        for _key, m, _peers in owners:
            m._restore(m._default_state())
        self._relink_groups()
        if serve_local:
            for key, m, peers in owners:
                # cache the fresh DELTA buffers before repointing the owner
                # at the snapshot — every member's unsync must restore the
                # delta side of the double buffer, never the snapshot
                fresh = {k: _copy_state_value(v) for k, v in m._state.items()}
                for x in [m] + peers:
                    x._cache = {k: _copy_state_value(v) for k, v in fresh.items()}
                    x._sync_degraded = False
                    x._mark_state_mutated("serve-local")
                    for name in x._state:
                        x._state[name] = snapshots[key][name]
                    x._is_synced = True
            self._sync_stats_dict()["served_local"] += 1

    def _clear_inflight(self):
        round_ = self.__dict__.get("_inflight_round")
        owners = self.__dict__.get("_inflight_owners")
        counts = self.__dict__.get("_inflight_counts")
        self._inflight_round = None
        self._inflight_owners = None
        self._inflight_counts = None
        for m in self.values():
            object.__setattr__(m, "_inflight_collection", None)
        return round_, owners, counts

    def _inflight_members(self, owners) -> List[Tuple[str, Metric, List[Metric]]]:
        """The launch-time owner map, split for members that copy-on-write
        detached from their group mid-flight: a detached member keeps its
        own delta and resolves against the same snapshot slice."""
        out: List[Tuple[str, Metric, List[Metric]]] = []
        for key, m, peers in owners:
            grouped = [
                p
                for p in peers
                if p._compute_group is not None and p._compute_group is m._compute_group
            ]
            out.append((key, m, grouped))
            for p in peers:
                if p not in grouped:
                    out.append((key, p, []))
        return out

    def _fold_back_overlap(self, combined_snapshot, owners, counts) -> None:
        """Restore every member's full local accumulation (its launch
        snapshot slice merged with its delta) — the before-any-raise step of
        every collection-round failure path."""
        for key, x, _grouped in self._inflight_members(owners):
            snapshot = {
                name: combined_snapshot[f"{key}{_FUSED_KEY_SEP}{name}"]
                for name in x._state
            }
            if getattr(x, "_update_count", 0) > counts[key]:
                delta = {k: _copy_state_value(v) for k, v in x._state.items()}
                x._restore(x.merge_states(snapshot, delta))
            else:
                x._restore(snapshot)
            x._cache = None
            g = x._compute_group
            if g is not None:
                self._relink_group(g, x)

    def _resolve_overlap(
        self,
        on_error: Optional[str] = None,
        timeout: Optional[float] = None,
        relaunch: bool = False,
        on_missing: Optional[str] = None,
    ) -> None:
        """Consume the collection's in-flight round and apply it to every
        member **all-or-nothing**: every member's policy view and restored
        local accumulation are computed first, then committed — a failure
        anywhere (the background task's typed error, or a
        ``staleness_policy="fresh"`` stale member) restores every member's
        full local accumulation and raises; the caller
        (:meth:`sync`) runs the degradation ladder. ``relaunch`` pipelines
        the next round from the restored accumulations."""
        round_, owners, counts = self._clear_inflight()
        stats = self._sync_stats_dict()
        try:
            synced, wait_s = resolve_round(round_, timeout=timeout)
        except SyncError:
            self._fold_back_overlap(round_.snapshot, owners, counts)
            raise
        stats["resolved"] += 1
        stats["gather_s"] += round_.gather_s
        stats["resolve_wait_s"] += wait_s
        stats["overlap_saved_s"] += max(0.0, round_.gather_s - wait_s)
        policy = getattr(self, "staleness_policy", "snapshot")
        members = self._inflight_members(owners)
        any_stale = any(
            getattr(x, "_update_count", 0) > counts[key] for key, x, _g in members
        )
        if journal.ACTIVE:
            journal.record(
                "sync.resolve", label="MetricCollection",
                sync_epoch=round_.epoch, stale=any_stale, policy=policy,
                verdict=("stale:" + policy) if any_stale else "fresh",
                wait_s=wait_s, gather_s=round_.gather_s,
                gather_start=round_.gather_started,
            )
        if any_stale:
            stats["stale_resolves"] += 1
            if policy == "fresh":
                self._fold_back_overlap(round_.snapshot, owners, counts)
                raise StaleSyncError(
                    f"overlapped sync round {round_.epoch} of this "
                    "MetricCollection resolved stale: update() ran after the "
                    "snapshot was taken (staleness_policy='fresh'). Resolve "
                    "before updating, or accept bounded staleness with "
                    "staleness_policy='snapshot'|'merge'."
                )
        # ---- all-or-nothing: compute every member's (view, local) first ----
        plans: List[Tuple[Metric, List[Metric], Dict[str, Any], Dict[str, Any]]] = []
        for key, x, grouped in members:
            snapshot = {
                name: round_.snapshot[f"{key}{_FUSED_KEY_SEP}{name}"] for name in x._state
            }
            gathered = {name: synced[f"{key}{_FUSED_KEY_SEP}{name}"] for name in x._state}
            if getattr(x, "_update_count", 0) > counts[key]:
                delta = {k: _copy_state_value(v) for k, v in x._state.items()}
                local = x.merge_states(snapshot, delta)
                view = x.merge_states(gathered, delta) if policy == "merge" else gathered
            else:
                local, view = snapshot, gathered
            plans.append((x, grouped, view, local))
        # ---- commit ----
        for x, grouped, view, local in plans:
            x._cache = local
            x._sync_degraded = False
            x._restore(view)
            x._is_synced = True
            for p in grouped:
                p._cache = {k: _copy_state_value(v) for k, v in local.items()}
                p._sync_degraded = False
                p._mark_state_mutated("overlap-resolve")
                for name in x._state:
                    p._state[name] = x._state[name]
                p._is_synced = True
        if relaunch:
            # pipeline: hand every member's restored accumulation (their
            # unsync caches) to the next round, leaving fresh delta buffers
            # for the paired unsync
            self._relaunch_from_caches(timeout, on_missing=on_missing)

    def _relaunch_from_caches(
        self, timeout: Optional[float], on_missing: Optional[str] = None
    ) -> None:
        """Pipeline relaunch: hand every member's restored accumulation (its
        unsync cache) to the next round, leaving fresh delta buffers for the
        paired unsync to restore."""
        owners = self._sync_state_owners()
        self._launch_combined(
            owners, lambda m: m._cache or m._state, timeout, on_missing=on_missing
        )
        for _key, m, peers in owners:
            fresh = m._default_state()
            m._cache = fresh
            for p in peers:
                p._cache = {k: _copy_state_value(v) for k, v in fresh.items()}

    def _resolve_member_request(
        self,
        member: Metric,
        on_error: Optional[str] = None,
        on_missing: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> None:
        """A single member's read (``compute()``/``sync()``/``state_dict()``)
        while a COLLECTION round covers its state: the whole round resolves
        (one future, all members applied all-or-nothing) and every member is
        left synced — restore them together with the collection's
        :meth:`unsync`. The requesting member's own sync context then
        unsyncs just that member, exactly as its blocking compute would."""
        self.sync(on_error=on_error, on_missing=on_missing, timeout=timeout, blocking=True)

    def _cancel_overlap(self) -> None:
        """The symmetric cancel for a collection round (``unsync()`` /
        ``reset()`` / ``clone()`` mid-flight): drain the round on every rank
        — never un-queue — discard the result or its error identically, and
        fold every member's snapshot slice back (see
        :meth:`Metric._cancel_overlap`)."""
        round_, owners, counts = self._clear_inflight()
        if round_ is None:
            return
        drain_round(round_)
        self._sync_stats_dict()["cancelled"] += 1
        if any(m._is_synced for m in self.values()):
            # mid-pipeline: the drained round owns the accumulations; the
            # members are serving the previous resolve — repoint their
            # unsync caches at the snapshot slices (updates were refused
            # while synced, so the delta caches are empty)
            for key, m, peers in owners:
                snap = {
                    name: round_.snapshot[f"{key}{_FUSED_KEY_SEP}{name}"]
                    for name in m._state
                }
                for x in [m] + peers:
                    x._cache = {k: _copy_state_value(v) for k, v in snap.items()}
            return
        self._fold_back_overlap(round_.snapshot, owners, counts)

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore every synced member's pre-sync local state.

        Members that degraded to local-only state (``on_error="local"``)
        were never marked synced and are skipped rather than raising.
        Compute-group views are re-linked afterwards (each member restored
        an equal-valued copy; re-aliasing keeps the one-copy-of-state
        invariant). A collection-level overlapped round that was launched
        but never resolved is **cancelled symmetrically** here: drained to
        completion on every rank, its result discarded, and every member's
        snapshot slice folded back (see :meth:`Metric._cancel_overlap`)."""
        if not should_unsync:
            return
        if self.__dict__.get("_inflight_round") is not None and not any(
            m._is_synced for m in self.values()
        ):
            self._cancel_overlap()
            return
        for m in self.values():
            if m._is_synced:
                m.unsync()
        self._relink_groups()

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
        on_error: Optional[str] = None,
        on_missing: Optional[str] = None,
        timeout: Optional[float] = None,
        blocking: Optional[bool] = None,
    ) -> Iterator["MetricCollection"]:
        """Collection-wide sync-on-enter / restore-on-exit (the consistent-
        checkpoint pattern), with ``on_error`` graceful degradation."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            should_sync=should_sync,
            distributed_available=distributed_available,
            on_error=on_error,
            on_missing=on_missing,
            timeout=timeout,
            blocking=blocking,
        )
        try:
            yield self
        finally:
            self.unsync(should_unsync=should_unsync)

    # ---------------- pure-functional fused path ----------------

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        # every member gets distinct fresh buffers (donation safety — see
        # Metric._default_state); compute-group dedup happens in pure_update,
        # whose outputs alias one subtree per group
        return {k: m.init_state() for k, m in super().items()}

    def _map_members_deduped(self, fn: Callable[[str, Metric], Any]) -> Dict[str, Any]:
        """Apply ``fn(key, member)`` per member with compute-group dedup: the
        group's first member in collection order runs it once and the result
        is aliased to every sibling key. Shared scaffolding of
        ``pure_update``/``pure_sync``/``merge_states``."""
        self._ensure_groups()
        out: Dict[str, Any] = {}
        group_results: Dict[int, Any] = {}
        for k, m in super().items():
            g = m._compute_group
            if g is not None and id(g) in group_results:
                out[k] = group_results[id(g)]
                continue
            result = fn(k, m)
            if g is not None:
                group_results[id(g)] = result
            out[k] = result
        return out

    def pure_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure functional update of every member's state subtree.

        Compute groups pay once: the group's first member traces ONE update
        over its subtree and the result is aliased to every sibling key —
        under jit the duplicate subtrees are the same tracers, so XLA emits
        a single update computation for the whole group.

        Caller contract for grouped collections: thread the WHOLE state
        through the collection-level ``pure_*`` methods. A group reads only
        its first member's subtree, so a sibling subtree mutated out of
        band (e.g. an extra per-member ``pure_update``) is superseded by
        the group result — the pure API has no per-call divergence
        detection (states may be tracers). For per-member divergence on
        the pure path, construct with ``compute_groups=False``."""
        return self._map_members_deduped(
            lambda k, m: m.pure_update(state[k], *args, **m._filtered_kwargs(kwargs))
        )

    def pure_sync(
        self, state: Dict[str, Any], axis_name: Optional[Any] = None, fused: bool = False
    ) -> Dict[str, Any]:
        """Collective-sync member states over ``axis_name``.

        ``axis_name=None``: each member syncs over its own declared
        ``process_group``; members without one keep their local state (what
        their standalone ``pure_forward`` would do). Raises if no member
        declares a group — there would be nothing to sync. ``fused=True``
        buckets each member's same-dtype/same-fx reduce leaves into one
        collective op (``sync_in_jit`` fused mode). Compute groups issue
        their collectives once and alias the result to every sibling key."""
        if axis_name is None and all(m.process_group is None for m in super().values()):
            raise MetricsTPUUserError(
                "pure_sync needs a mesh axis: pass `axis_name=` or construct "
                "at least one member with `process_group=<axis or tuple>`."
            )

        def sync_one(k: str, m: Metric) -> Any:
            if axis_name is not None:
                return m.pure_sync(state[k], axis_name, fused=fused)
            if m.process_group is not None:
                return m.pure_sync(state[k], fused=fused)
            return state[k]

        return self._map_members_deduped(sync_one)

    def pure_compute(self, state: Dict[str, Any]) -> Dict[str, Any]:
        return {self._set_name(k): m.pure_compute(state[k]) for k, m in super().items()}

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        return self._map_members_deduped(lambda k, m: m.merge_states(a[k], b[k]))

    def pure_forward(
        self, state: Dict[str, Any], *args: Any, axis_name: Optional[str] = None, **kwargs: Any
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """One fused jittable step for the WHOLE collection: all member
        updates, one round of collectives, all computes — a single XLA graph.

        With ``axis_name=None`` each member syncs over its own declared
        ``process_group`` (members without one stay local) — exactly what the
        member's standalone ``pure_forward`` would do, so mixed-group
        collections neither skip a declared sync nor force one on a
        group-less member."""
        batch = self.pure_update(self.init_state(), *args, **kwargs)
        any_group = any(m.process_group is not None for m in super().values())
        if axis_name is not None or any_group:
            value_state = self.pure_sync(batch, axis_name)
        else:
            value_state = batch
        values = self.pure_compute(value_state)
        new_state = self.merge_states(state, batch)
        return new_state, values

    def compiled_step(
        self,
        state: Dict[str, Any],
        *args: Any,
        axis_name: Optional[Any] = None,
        **kwargs: Any,
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """The whole-step fused program for the WHOLE collection: every
        member's ``update``, ONE fused in-jit sync round, every member's
        ``compute`` — cached as a single XLA program (bench config 15).

        Returns ``(new_state, values)``: ``values`` holds what a blocking
        ``sync(); compute()`` of the accumulated state would serve per
        member key, with the collective issued inside the program so XLA
        schedules it against the metric computes — a periodic per-step
        ``compute()`` adds zero extra dispatches. Inside a jit/pjit/
        ``shard_map`` step it inlines into the user's one program; eagerly
        it dispatches a cached donated program (thread ``new_state``
        forward like a scan carry). Managed by ``core/plan.py``
        (``METRICS_TPU_UNIFIED_PLAN=0`` restores the legacy separate-phase
        composition).
        """
        return plan_mod.compiled_step(self, state, args, kwargs, axis_name=axis_name)

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "(\n"
        for k, v in super().items():
            repr_str += f"  ({k}): {repr(v)}\n"
        return repr_str + ")"
