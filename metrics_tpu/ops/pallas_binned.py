"""Pallas TPU kernel for the binned PR-curve hot op.

The binned family (reference ``torchmetrics/classification/
binned_precision_recall.py:147-174``) accumulates TP/FP/FN counts of shape
``[num_classes, num_thresholds]`` from ``[N, C]`` probability batches. The
straightforward XLA formulation broadcasts an ``[N, C, T]`` boolean
comparison and reduces over N — at large ``N*C*T`` that materializes
multi-hundred-MB intermediates in HBM.

This kernel restructures the op for the TPU memory hierarchy:

- inputs are transposed to **class-major** ``[C, N]`` so the class axis rides
  the sublanes and the batch axis rides the 128-wide lanes;
- the batch is **streamed through VMEM once** in ``[C, block]`` tiles; per
  tile, thresholds are processed in small chunks, each chunk doing a
  ``[TC, C, block]`` compare + lane-reduction on the VPU — nothing of size
  ``N*T`` ever exists in HBM;
- the ``[T, C]`` TP/count accumulators live in VMEM across grid steps;
  FP and FN are derived algebraically (``FP = CNT - TP``, ``FN = POS - TP``).

Use :func:`binned_stat_scores` — it dispatches to the kernel on TPU backends
and to the fused-XLA path elsewhere (CPU tests run the kernel in interpreter
mode to validate it against the XLA path).
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

__all__ = ["binned_stat_scores"]

_LANE = 128  # TPU lane width
_SUBLANE = 8  # float32 sublane tile
_BLOCK_N = 2048  # batch elements per grid step (lane-dim tiles)
_THRESH_CHUNK = 16  # thresholds per inner-loop step


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _binned_stats_xla(preds: Array, target: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """Fused-XLA reference path: broadcast compare + reduce (CPU default).

    Compares in float32 like the pallas kernel does, so inputs lying exactly
    at a threshold classify identically on both backends."""
    preds = preds.astype(jnp.float32)
    thresholds = thresholds.astype(jnp.float32)
    predictions = preds[:, :, None] >= thresholds[None, None, :]
    t = target[:, :, None].astype(bool)
    tp = jnp.sum(t & predictions, axis=0).astype(jnp.float32)
    fp = jnp.sum(~t & predictions, axis=0).astype(jnp.float32)
    fn = jnp.sum(t & ~predictions, axis=0).astype(jnp.float32)
    return tp, fp, fn


def _kernel(x_ref, w_ref, thr_ref, tp_ref, cnt_ref, pos_ref, *, t_chunks: int):
    """One grid step: a [C, block] tile of the class-major stream.

    x_ref/w_ref: [Cp, BN] probabilities / {0,1} weights.
    thr_ref:     [Tp, 1] thresholds.
    tp_ref/cnt_ref: [Tp, Cp] accumulators; pos_ref: [1, Cp].
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        tp_ref[:] = jnp.zeros_like(tp_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        pos_ref[:] = jnp.zeros_like(pos_ref)

    x = x_ref[:]  # [Cp, BN]
    w = w_ref[:]

    def body(k, _):
        i0 = k * _THRESH_CHUNK
        thr_c = thr_ref[pl.ds(i0, _THRESH_CHUNK), :]  # [TC, 1]
        # [TC, Cp, BN] compare lives only in registers/VMEM for this chunk
        cmp = (x[None, :, :] >= thr_c[:, :, None]).astype(jnp.float32)
        tp_ref[pl.ds(i0, _THRESH_CHUNK), :] += jnp.sum(w[None, :, :] * cmp, axis=2)
        cnt_ref[pl.ds(i0, _THRESH_CHUNK), :] += jnp.sum(cmp, axis=2)
        return 0

    jax.lax.fori_loop(0, t_chunks, body, 0)
    pos_ref[0, :] += jnp.sum(w, axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_stats_pallas(
    preds: Array, target: Array, thresholds: Array, interpret: bool = False
) -> Tuple[Array, Array, Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, c = preds.shape
    t = thresholds.shape[0]
    tp_pad = _ceil_to(t, max(_THRESH_CHUNK, _SUBLANE))
    cp = _ceil_to(c, _SUBLANE)
    block = min(_BLOCK_N, _ceil_to(n, _LANE))
    np_ = _ceil_to(n, block)

    # class-major stream; batch padding gets -inf probs (matches no finite
    # threshold) / 0 weights, threshold padding is +inf (matches no element)
    x = jnp.full((cp, np_), -jnp.inf, jnp.float32)
    x = x.at[:c, :n].set(preds.T.astype(jnp.float32))
    w = jnp.zeros((cp, np_), jnp.float32).at[:c, :n].set(target.T.astype(jnp.float32))
    thr = jnp.full((tp_pad, 1), jnp.inf, jnp.float32).at[:t, 0].set(thresholds.astype(jnp.float32))

    kernel = functools.partial(_kernel, t_chunks=tp_pad // _THRESH_CHUNK)
    tp, cnt, pos = pl.pallas_call(
        kernel,
        grid=(np_ // block,),
        in_specs=[
            pl.BlockSpec((cp, block), lambda i: (0, i)),
            pl.BlockSpec((cp, block), lambda i: (0, i)),
            pl.BlockSpec((tp_pad, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tp_pad, cp), lambda i: (0, 0)),
            pl.BlockSpec((tp_pad, cp), lambda i: (0, 0)),
            pl.BlockSpec((1, cp), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tp_pad, cp), jnp.float32),
            jax.ShapeDtypeStruct((tp_pad, cp), jnp.float32),
            jax.ShapeDtypeStruct((1, cp), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, thr)

    tp = tp[:t, :c].T  # [C, T]
    fp = cnt[:t, :c].T - tp
    fn = pos[0, :c, None] - tp
    return tp, fp, fn


def _vmem_budget_ok(n: int, c: int, t: int) -> bool:
    """Live VMEM: in tiles + [Tp,Cp] accumulators + one [TC,Cp,block] chunk."""
    cp = _ceil_to(c, _SUBLANE)
    tp_pad = _ceil_to(t, max(_THRESH_CHUNK, _SUBLANE))
    block = min(_BLOCK_N, _ceil_to(n, _LANE))
    live = (2 * cp * block + 2 * tp_pad * cp + 2 * _THRESH_CHUNK * cp * block) * 4
    return live < 8 * 1024 * 1024


def binned_stat_scores(
    preds: Array,
    target: Array,
    thresholds: Array,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-class, per-threshold (TP, FP, FN) counts for binned PR metrics.

    Args:
        preds: ``[N, C]`` probabilities.
        target: ``[N, C]`` binary labels.
        thresholds: ``[T]`` decision thresholds.
        use_pallas: force the kernel on/off; default auto (TPU backend only,
            within VMEM budget).
        interpret: run the kernel in interpreter mode (CPU testing).

    Returns:
        Three ``[C, T]`` float32 arrays: true/false positives and false
        negatives at each (class, threshold).
    """
    n, c = preds.shape
    t = thresholds.shape[0]
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and _vmem_budget_ok(n, c, t)
    if use_pallas or interpret:
        return _binned_stats_pallas(preds, target, thresholds, interpret=interpret)
    return _binned_stats_xla(preds, target, thresholds)
