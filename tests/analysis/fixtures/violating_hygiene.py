"""metricslint fixture: declaration-hygiene violations — identity redeclare,
unshared latches, statically-wrong add_state defaults.

The CI gate asserts the CLI exits NONZERO on this file.
"""
import jax.numpy as jnp


class FamilyBase:
    """declares a grouping key: its update is a correctness promise."""

    _group_shared_attrs = ("mode",)

    def __init__(self):
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.mode = None

    def add_state(self, *a, **k):
        pass

    def update_identity(self):
        return ("family", 1)

    def update(self, x):
        self.mode = "binary"  # clean: declared in _group_shared_attrs
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class OverridesUpdateOnly(FamilyBase):
    """finding: update-identity-redeclare — inherits FamilyBase's key but
    replaces the update it described; the runtime silently drops the key."""

    def update(self, x):  # finding on this line
        self.total = self.total + jnp.sum(x) * 2


class UnsharedLatchFamily(FamilyBase):
    """finding: unshared-latch — declares (inherits) an identity, but its
    update mutates an attribute missing from _group_shared_attrs."""

    def update_identity(self):
        return ("unshared", 1)

    def update(self, x):
        self.num_classes = int(x.shape[-1])  # finding: unshared-latch
        self.total = self.total + jnp.sum(x)


class BadDefaults:
    def __init__(self):
        # finding: state-default (non-empty list default)
        self.add_state("filled", [1, 2], dist_reduce_fx="cat")
        # finding: state-default (invalid fx literal)
        self.add_state("bad_fx", jnp.zeros(()), dist_reduce_fx="prod")
        # finding: state-default (growing list with reduce-style fx)
        self.add_state("list_sum", [], dist_reduce_fx="sum")
        # finding: state-default (0-d default on a 'cat' state)
        self.add_state("scalar_cat", jnp.zeros(()), dist_reduce_fx="cat")
        # finding: state-default (duplicate declaration)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def add_state(self, *a, **k):
        pass

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total
