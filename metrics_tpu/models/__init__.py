from metrics_tpu.models.inception import (  # noqa: F401
    InceptionFeatureExtractor,
    inception_v3_apply,
    inception_v3_init,
    load_torch_inception_weights,
)
