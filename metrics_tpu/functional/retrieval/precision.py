"""Single-query precision@k — analogue of reference
``torchmetrics/functional/retrieval/precision.py``."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_retrieval_k, _check_retrieval_functional_inputs


def retrieval_precision(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of relevant documents among the top ``k`` retrieved.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([True, False, True])
        >>> print(round(float(retrieval_precision(preds, target, k=2)), 4))
        0.5
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    _check_retrieval_k(k)
    if not jnp.sum(target):
        return jnp.asarray(0.0)
    relevant = jnp.sum(target[jnp.argsort(-preds)][:k]).astype(jnp.float32)
    return relevant / k
