"""metricslint metric-class pass: rule-by-rule coverage over the violation /
clean / suppressed fixtures plus inline sources for the edge cases."""
import os

import pytest

from metrics_tpu.analysis import analyze_paths, analyze_source
from metrics_tpu.analysis.metric_pass import RUNTIME_EXEMPT_ATTRS

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def rules_of(findings):
    return {f.rule for f in findings}


def analyze_fixture(name: str):
    findings, errors = analyze_paths([fixture(name)])
    assert not errors
    return findings


# ---------------------------------------------------------------------------
# fixture files: each violating file trips exactly its rules, clean trips none
# ---------------------------------------------------------------------------

def test_undeclared_state_fixture_variants():
    findings = analyze_fixture("violating_undeclared_state.py")
    assert rules_of(findings) == {"undeclared-state"}
    attrs = {f.attr for f in findings}
    # plain assign, in-place append, in-place [k]=, aug-assign, helper write,
    # compute-side write — every variant is caught
    assert attrs == {"seen", "shapes", "by_kind", "calls", "last_batch", "cached"}
    # declared states never fire
    assert not any(f.attr in ("total", "rows") for f in findings)


def test_host_sync_fixture_variants():
    findings = analyze_fixture("violating_host_sync.py")
    assert rules_of(findings) == {"host-sync-in-update"}
    msgs = " | ".join(f.message for f in findings)
    for needle in ("float()", ".item()", "np.asarray", "device_get", "int()"):
        assert needle in msgs, f"missing variant: {needle}"


def test_hygiene_fixture_variants():
    findings = analyze_fixture("violating_hygiene.py")
    assert rules_of(findings) == {
        "update-identity-redeclare", "unshared-latch", "state-default",
    }
    defaults = [f for f in findings if f.rule == "state-default"]
    joined = " | ".join(f.message for f in defaults)
    for needle in ("EMPTY list", "'prod'", "growing list", "0-d default", "duplicate"):
        assert needle in joined, f"missing state-default variant: {needle}"
    latch = next(f for f in findings if f.rule == "unshared-latch")
    assert latch.attr == "num_classes"


def test_clean_fixture_has_no_findings():
    assert analyze_fixture("clean_metric.py") == []


def test_suppressed_fixture_has_no_findings():
    assert analyze_fixture("suppressed_metric.py") == []


def test_suppression_is_rule_specific():
    src = open(fixture("suppressed_metric.py")).read()
    # narrow the same-line suppression to the WRONG rule: finding comes back
    bad = src.replace(
        "# metricslint: disable=undeclared-state", "# metricslint: disable=state-default"
    )
    findings = analyze_source(bad, "suppressed_metric.py")
    assert "undeclared-state" in rules_of(findings)


# ---------------------------------------------------------------------------
# inline edge cases
# ---------------------------------------------------------------------------

SNIPPET = '''
import jax.numpy as jnp

class M:
    def __init__(self):
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
    def add_state(self, *a, **k):
        pass
    def update(self, x):
        {body}
    def compute(self):
        return self.total
'''


def _one(body: str):
    return analyze_source(SNIPPET.format(body=body), "<snippet>")


def test_runtime_bookkeeping_attrs_are_exempt():
    assert _one("self._update_count = 3; self.total = self.total + jnp.sum(x)") == []


def test_setattr_with_constant_name_is_caught():
    findings = _one('setattr(self, "latch", 1); self.total = self.total + jnp.sum(x)')
    assert [f.attr for f in findings] == ["latch"]


def test_dynamic_state_names_stay_silent():
    # add_state name built dynamically: the declared set is unknowable, so
    # the mutation rules must not guess
    src = '''
import jax.numpy as jnp

class M:
    def __init__(self, keys):
        for k in keys:
            self.add_state(f"{k}_sum", jnp.zeros(()), dist_reduce_fx="sum")
    def add_state(self, *a, **k):
        pass
    def update(self, x):
        self.anything = 1
    def compute(self):
        return 0
'''
    assert analyze_source(src, "<snippet>") == []


def test_conditional_alternative_declarations_are_not_duplicates():
    src = '''
import jax.numpy as jnp

class M:
    def __init__(self, samplewise):
        if samplewise:
            self.add_state("v", [], dist_reduce_fx="cat")
        else:
            self.add_state("v", jnp.zeros(()), dist_reduce_fx="sum")
    def add_state(self, *a, **k):
        pass
    def update(self, x):
        self.v = self.v + jnp.sum(x)
    def compute(self):
        return self.v
'''
    assert analyze_source(src, "<snippet>") == []


def test_cross_file_inheritance_resolves_states(tmp_path):
    base = tmp_path / "base_mod.py"
    base.write_text('''
import jax.numpy as jnp

class Base:
    def __init__(self):
        for s in ("tp", "fp"):
            self.add_state(s, jnp.zeros(()), dist_reduce_fx="sum")
    def add_state(self, *a, **k):
        pass
    def update(self, x):
        self.tp = self.tp + 1
    def compute(self):
        return self.tp
''')
    child = tmp_path / "child_mod.py"
    child.write_text('''
from base_mod import Base

class Child(Base):
    def update(self, x):
        self.fp = self.fp + 1   # declared in the OTHER file's Base
        self.stray = 1          # finding
''')
    findings, errors = analyze_paths([str(tmp_path)])
    assert not errors
    assert [(f.rule, f.attr) for f in findings] == [("undeclared-state", "stray")]


def test_exempt_set_matches_runtime_probe():
    """The AST pass must never flag what the runtime probe exempts — the
    static copy has to stay a superset of core.compiled._PROBE_EXEMPT."""
    from metrics_tpu.core.compiled import _PROBE_EXEMPT

    missing = set(_PROBE_EXEMPT) - set(RUNTIME_EXEMPT_ATTRS)
    assert not missing, f"RUNTIME_EXEMPT_ATTRS is missing {sorted(missing)}"


def test_shipped_package_is_clean():
    """The acceptance gate, as a test: the CLI contract over metrics_tpu/."""
    import metrics_tpu

    pkg = os.path.dirname(metrics_tpu.__file__)
    findings, errors = analyze_paths([pkg])
    assert not errors
    assert findings == [], "\n".join(f.format() for f in findings)
