"""MatthewsCorrcoef module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/matthews_corrcoef.py`` (114 LoC).
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.matthews_corrcoef import (
    _matthews_corrcoef_compute,
    _matthews_corrcoef_update,
)


class MatthewsCorrcoef(Metric):
    r"""Matthews correlation coefficient — the correlation between
    predicted and true labels, computed from a full accumulated confusion
    matrix. Unlike accuracy or F1 it uses all four counts symmetrically,
    making it the robust single number under class imbalance: +1 perfect,
    0 chance, −1 total disagreement.

    State is the ``[C, C]`` confusion-matrix sum leaf (one ``psum``).
    Degenerate marginals (an all-one-class stream) yield NaN, matching
    the reference and sklearn (0/0).

    Args:
        num_classes: number of classes (sets the static state shape).
        threshold: binarization cut for probabilistic input.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MatthewsCorrcoef
        >>> preds = jnp.asarray([1, 0, 1, 1])
        >>> target = jnp.asarray([1, 0, 0, 1])
        >>> matthews = MatthewsCorrcoef(num_classes=2)
        >>> print(round(float(matthews(preds, target)), 4))
        0.5774
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: int,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.threshold = threshold
        self.add_state(
            "confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.float32), dist_reduce_fx="sum"
        )

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        confmat = _matthews_corrcoef_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _matthews_corrcoef_compute(self.confmat)
