"""AveragePrecision module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/average_precision.py`` (150 LoC).
"""
from typing import Any, Callable, List, Optional, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.ops.ranking import (
    masked_binary_average_precision,
    masked_multiclass_average_precision,
)
from metrics_tpu.utils.data import dim_zero_cat


class AveragePrecision(Metric):
    r"""Average precision :math:`\sum_k (R_k - R_{k-1}) P_k` — the area
    under the precision–recall step curve (reference
    ``average_precision.py``). Favoured over ROC-AUC when positives are
    rare, because it never rewards easy true negatives.

    Scores/targets accumulate as "cat" states (list-of-batches by
    default, or a fixed-capacity :class:`~metrics_tpu.CatBuffer` via
    ``with_capacity`` for a constant-shape jitted update; padding rows
    are masked out of the ranking at compute).

    Args:
        num_classes: number of classes for multiclass scores ``[N, C]``;
            ``None`` for binary ``[N]``.
        pos_label: the label treated as positive in binary input.
        average: ``"macro"`` (equal-weight mean of per-class APs),
            ``"weighted"`` (support-weighted mean), ``"micro"`` (pool all
            decisions), or ``None`` (per-class list).
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> average_precision = AveragePrecision()
        >>> print(round(float(average_precision(preds, target)), 4))
        0.8333
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    #: the shared clf-curve preprocessing infers num_classes/pos_label; a
    #: grouped dispatch copies the inference to every sibling
    _group_shared_attrs = ("num_classes", "pos_label")

    def update_identity(self):
        """Compute-group key. ``_average_precision_update`` delegates to
        ``_precision_recall_curve_update`` and, for every ``average`` except
        ``"micro"``, returns its result untouched — so non-micro instances
        share the clf-curve family key (ROC / PrecisionRecallCurve /
        AveragePrecision with equal ``(num_classes, pos_label)`` hold one
        preds/target accumulation). ``"micro"`` additionally ravels
        multilabel input and only groups with other micro instances."""
        if self.average == "micro":
            return ("clf_curve_micro", self.num_classes, self.pos_label)
        return ("clf_curve", self.num_classes, self.pos_label)

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(self) -> Union[Array, List[Array]]:
        # Binary CatBuffer mode: static-shape step-integral AP with tie-group
        # segment sums (ops/ranking.py) — update + sync + compute fuse into
        # one jitted program; the curve path needs data-dependent
        # unique-threshold sizes and is eager-only. Same value incl. ties.
        if isinstance(self._state["preds"], CatBuffer):
            preds_cb: CatBuffer = self._state["preds"]
            target_cb: CatBuffer = self._state["target"]
            if self.num_classes == 1 and self.pos_label == 1:
                if preds_cb.buffer is None:
                    raise ValueError("No samples to concatenate")
                # binarize exactly like the curve path (`target == pos_label` in
                # `_binary_clf_curve`) — raw targets may hold values outside {0,1}
                binary_target = (target_cb.buffer == self.pos_label).astype(jnp.float32)
                # poison: an in-jit overflow overwrote rows -> NaN, not a
                # plausible wrong AP (cat_buffer.py `poison` contract)
                return preds_cb.poison(
                    masked_binary_average_precision(
                        preds_cb.buffer, binary_target, preds_cb.mask()
                    )
                )
            # one-vs-rest vectorized masked path for multiclass [N, C] scores:
            # per-class AP under one vmap, NaN classes excluded from the
            # average like the eager path's nan-filter
            if (
                preds_cb.buffer is not None
                and preds_cb.buffer.ndim == 2
                and target_cb.buffer.ndim == 1
                and self.average != "micro"
            ):
                res = preds_cb.poison(
                    masked_multiclass_average_precision(
                        preds_cb.buffer, target_cb.buffer, preds_cb.mask(), self.average
                    )
                )
                if self.average is None:
                    # list-of-scalars like the eager path, so the return type
                    # doesn't flip with with_capacity()
                    return [res[c] for c in range(preds_cb.buffer.shape[1])]
                return res
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _average_precision_compute(
            preds, target, self.num_classes, self.pos_label, self.average
        )
